"""THR001 fixtures: cross-thread field writes."""

import threading


class Exporter:
    """Writer thread + main both mutate ``n_written`` unguarded."""

    def __init__(self):
        self.n_written = 0
        self.started = False

    def start(self):
        self.started = True
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.n_written += 1        # expect: THR001

    def reset(self):
        self.n_written = 0


class LockedExporter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n_written = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.n_written += 1

    def reset(self):
        with self._lock:
            self.n_written = 0


class AnnotatedExporter:
    """Single-writer-by-design: the annotation makes the choice visible."""

    def __init__(self):
        self.n_written = 0  # guarded-by: GIL last-write-wins, monitoring only

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.n_written += 1

    def reset(self):
        self.n_written = 0


class Poller:
    """Entry designated via LintConfig.thread_entries (no Thread() call in
    sight — the poll comes from another component's thread)."""

    def __init__(self):
        self.state = "idle"

    def poll(self):
        self.state = "polled"      # expect: THR001

    def reset(self):
        self.state = "idle"

    def read_only(self):
        return self.state


class SingleWriter:
    """Thread entry writes; main only reads — clean."""

    def __init__(self):
        self.count = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.count += 1

    def snapshot(self):
        return self.count
