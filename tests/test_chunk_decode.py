"""chunk_decode (batched multi-token pass — the spec-decode verify/catch-up
primitive): parity with sequential single-token decode."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama

CFG = get_config("tiny")


def _prefill_row(params, cache, prompt, table):
    logits, k, v = llama.prefill(
        params, CFG, cache.k, cache.v,
        jnp.asarray(prompt, dtype=jnp.int32), jnp.int32(len(prompt)), jnp.int32(0), table,
    )
    return int(jnp.argmax(logits)), k, v


def test_chunk_decode_matches_sequential():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = list(range(30, 46))
    table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)

    # Sequential reference: 4 single-token decode steps.
    cache = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    t0, k, v = _prefill_row(params, cache, prompt, table)
    chunk = [t0, 7, 8, 9]  # arbitrary continuation tokens
    B = 2
    tables = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(table)
    seq_preds = []
    pos = 16
    for t in chunk:
        logits, k, v = llama.decode(
            params, CFG, k, v,
            jnp.array([t, 0], dtype=jnp.int32), jnp.array([pos, 0], dtype=jnp.int32),
            tables, jnp.array([True, False]),
        )
        seq_preds.append(int(jnp.argmax(logits[0])))
        pos += 1

    # Chunk pass: same 4 tokens in one dispatch (row 1 inactive).
    cache2 = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    _, k2, v2 = _prefill_row(params, cache2, prompt, table)
    toks = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(jnp.array(chunk, dtype=jnp.int32))
    preds, k2, v2 = llama.chunk_decode(
        params, CFG, k2, v2, toks,
        jnp.array([16, 0], dtype=jnp.int32), jnp.array([4, 0], dtype=jnp.int32), tables,
    )
    assert [int(t) for t in preds[0]] == seq_preds
    # Cache rows written by the chunk match the sequential writes (real blocks).
    np.testing.assert_allclose(np.asarray(k2[:, 1:4]), np.asarray(k[:, 1:4]), rtol=1e-5, atol=1e-5)


def test_chunk_decode_partial_valid():
    """A row with valid=2 consumes only 2 tokens; predictions beyond valid
    are don't-care and the cache only gains 2 rows."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = list(range(30, 46))
    table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)
    cache = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    t0, k, v = _prefill_row(params, cache, prompt, table)

    B = 1
    tables = table[None, :]
    toks = jnp.array([[t0, 5, 99, 99]], dtype=jnp.int32)
    preds, k2, v2 = llama.chunk_decode(
        params, CFG, k, v, toks, jnp.array([16]), jnp.array([2]), tables,
    )

    # Reference: two sequential steps.
    cache2 = KvCacheArrays.create(CFG, 24, dtype=jnp.float32)
    _, kr, vr = _prefill_row(params, cache2, prompt, table)
    ref = []
    for i, t in enumerate([t0, 5]):
        logits, kr, vr = llama.decode(
            params, CFG, kr, vr, jnp.array([t], dtype=jnp.int32),
            jnp.array([16 + i], dtype=jnp.int32), tables, jnp.array([True]),
        )
        ref.append(int(jnp.argmax(logits[0])))
    assert [int(t) for t in preds[0][:2]] == ref
    # Position 18 (= slot 2 of block 2... block index 18//16=1 → table[1]=2,
    # offset 2) must NOT have been written by the chunk pass.
    np.testing.assert_allclose(np.asarray(k2[:, 2, 2]), np.asarray(kr[:, 2, 2]), atol=1e-6)
