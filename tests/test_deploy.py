"""Deploy tooling: graph spec, manifest rendering, local operator reconcile.
Ref: deploy/cloud operator + CRDs (SURVEY.md §2 N12)."""

import asyncio
import sys

import pytest
import yaml

from dynamo_tpu.deploy import (
    GraphConnector,
    GraphDeployment,
    LocalOperator,
    render_manifests,
)
from dynamo_tpu.deploy.manifests import render_yaml

GRAPH_YAML = """
name: tiny-disagg
namespace: prod
control_plane: tcp://cp.internal:6650
services:
  frontend:
    command: [python, -m, dynamo_tpu.frontend, --router-mode, kv]
    replicas: 1
  decode:
    command: [python, -m, dynamo_tpu.worker, --model, llama-3-8b]
    replicas: 2
    resources: {tpu_chips: 4, memory: 32Gi}
    env: {BENCH_ATTN: paged_kernel}
"""


def test_spec_yaml_roundtrip():
    g = GraphDeployment.from_yaml(GRAPH_YAML)
    assert g.name == "tiny-disagg" and g.namespace == "prod"
    assert g.services["decode"].replicas == 2
    assert g.services["decode"].resources.tpu_chips == 4
    g2 = GraphDeployment.from_yaml(g.to_yaml())
    assert g2.to_dict() == g.to_dict()
    env = g.base_env()
    assert env["DYN_CONTROL_PLANE"] == "tcp"
    assert env["DYN_CONTROL_PLANE_ADDRESS"] == "cp.internal:6650"


def test_spec_validation():
    with pytest.raises(ValueError):
        GraphDeployment.from_dict({"name": "x", "services": {}})
    with pytest.raises(ValueError):
        GraphDeployment.from_dict({"name": "x", "services": {"a": {"replicas": 1}}})


def test_render_manifests():
    g = GraphDeployment.from_yaml(GRAPH_YAML)
    ms = render_manifests(g, image="gcr.io/p/dynamo-tpu:1", tpu_accelerator="tpu-v5-lite-podslice")
    kinds = [(m["kind"], m["metadata"]["name"]) for m in ms]
    assert ("Deployment", "tiny-disagg-frontend") in kinds
    assert ("Deployment", "tiny-disagg-decode") in kinds
    assert ("Service", "tiny-disagg-frontend") in kinds  # frontend exposed
    assert ("Service", "tiny-disagg-decode") not in kinds

    decode = next(m for m in ms if m["metadata"]["name"] == "tiny-disagg-decode")
    c = decode["spec"]["template"]["spec"]["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    assert decode["spec"]["template"]["spec"]["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"
    ] == "tpu-v5-lite-podslice"
    assert decode["spec"]["replicas"] == 2
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["DYN_NAMESPACE"] == "prod" and env["BENCH_ATTN"] == "paged_kernel"

    docs = list(yaml.safe_load_all(render_yaml(g)))
    assert len(docs) == len(render_manifests(g))


def _sleep_graph(replicas=1):
    return GraphDeployment.from_dict({
        "name": "t",
        "services": {
            "w": {"command": [sys.executable, "-c", "import time; time.sleep(60)"], "replicas": replicas},
        },
    })


async def test_operator_scale_up_down():
    op = LocalOperator(_sleep_graph(2), grace_s=2.0)
    try:
        await op.reconcile()
        assert op.status()["w"]["live"] == 2
        op.set_replicas("w", 1)
        await op.reconcile()
        assert op.status()["w"]["live"] == 1
        conn = GraphConnector(op)
        await conn.set_replicas("w", 3)
        assert op.status()["w"]["live"] == 3
        assert await conn.get_replicas("w") == 3
    finally:
        await op.shutdown()
    assert op.status()["w"]["live"] == 0


async def test_operator_restarts_crashed_child():
    g = GraphDeployment.from_dict({
        "name": "t",
        "services": {"w": {"command": [sys.executable, "-c", "pass"], "replicas": 1}},
    })
    op = LocalOperator(g, grace_s=1.0, max_restarts=50)
    try:
        await op.reconcile()
        first = op._children["w"][0]
        await first.proc.wait()  # exits immediately
        await op.reconcile()  # reaps + respawns
        assert op.status()["w"]["live"] == 1
        assert op._children["w"][0] is not first
    finally:
        await op.shutdown()


async def test_operator_crash_loop_marks_degraded():
    g = GraphDeployment.from_dict({
        "name": "t",
        "services": {"w": {"command": [sys.executable, "-c", "raise SystemExit(1)"], "replicas": 1}},
    })
    op = LocalOperator(g, max_restarts=3, restart_window_s=60.0)
    try:
        for _ in range(10):
            await op.reconcile()
            for c in op._children["w"]:
                await c.proc.wait()
            if op.status()["w"]["degraded"]:
                break
            await asyncio.sleep(0.02)
        st = op.status()["w"]
        assert st["degraded"] and st["live"] == 0  # backs off, stops respawning
    finally:
        await op.shutdown()
