"""Deploy tooling: graph spec, manifest rendering, local operator reconcile.
Ref: deploy/cloud operator + CRDs (SURVEY.md §2 N12)."""

import asyncio
import sys

import pytest
import yaml

from dynamo_tpu.deploy import (
    GraphConnector,
    GraphDeployment,
    LocalOperator,
    render_manifests,
)
from dynamo_tpu.deploy.manifests import render_yaml

GRAPH_YAML = """
name: tiny-disagg
namespace: prod
control_plane: tcp://cp.internal:6650
services:
  frontend:
    command: [python, -m, dynamo_tpu.frontend, --router-mode, kv]
    replicas: 1
  decode:
    command: [python, -m, dynamo_tpu.worker, --model, llama-3-8b]
    replicas: 2
    resources: {tpu_chips: 4, memory: 32Gi}
    env: {BENCH_ATTN: gather}
"""


def test_spec_yaml_roundtrip():
    g = GraphDeployment.from_yaml(GRAPH_YAML)
    assert g.name == "tiny-disagg" and g.namespace == "prod"
    assert g.services["decode"].replicas == 2
    assert g.services["decode"].resources.tpu_chips == 4
    g2 = GraphDeployment.from_yaml(g.to_yaml())
    assert g2.to_dict() == g.to_dict()
    env = g.base_env()
    assert env["DYN_CONTROL_PLANE"] == "tcp"
    assert env["DYN_CONTROL_PLANE_ADDRESS"] == "cp.internal:6650"


def test_spec_validation():
    with pytest.raises(ValueError):
        GraphDeployment.from_dict({"name": "x", "services": {}})
    with pytest.raises(ValueError):
        GraphDeployment.from_dict({"name": "x", "services": {"a": {"replicas": 1}}})


def test_render_manifests():
    g = GraphDeployment.from_yaml(GRAPH_YAML)
    ms = render_manifests(g, image="gcr.io/p/dynamo-tpu:1", tpu_accelerator="tpu-v5-lite-podslice")
    kinds = [(m["kind"], m["metadata"]["name"]) for m in ms]
    assert ("Deployment", "tiny-disagg-frontend") in kinds
    assert ("Deployment", "tiny-disagg-decode") in kinds
    assert ("Service", "tiny-disagg-frontend") in kinds  # frontend exposed
    assert ("Service", "tiny-disagg-decode") not in kinds

    decode = next(m for m in ms if m["metadata"]["name"] == "tiny-disagg-decode")
    c = decode["spec"]["template"]["spec"]["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "4"
    assert decode["spec"]["template"]["spec"]["nodeSelector"][
        "cloud.google.com/gke-tpu-accelerator"
    ] == "tpu-v5-lite-podslice"
    assert decode["spec"]["replicas"] == 2
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["DYN_NAMESPACE"] == "prod" and env["BENCH_ATTN"] == "gather"

    docs = list(yaml.safe_load_all(render_yaml(g)))
    assert len(docs) == len(render_manifests(g))


def _sleep_graph(replicas=1):
    return GraphDeployment.from_dict({
        "name": "t",
        "services": {
            "w": {"command": [sys.executable, "-c", "import time; time.sleep(60)"], "replicas": replicas},
        },
    })


async def test_operator_scale_up_down():
    op = LocalOperator(_sleep_graph(2), grace_s=2.0)
    try:
        await op.reconcile()
        assert op.status()["w"]["live"] == 2
        op.set_replicas("w", 1)
        await op.reconcile()
        assert op.status()["w"]["live"] == 1
        conn = GraphConnector(op)
        await conn.set_replicas("w", 3)
        assert op.status()["w"]["live"] == 3
        assert await conn.get_replicas("w") == 3
    finally:
        await op.shutdown()
    assert op.status()["w"]["live"] == 0


async def test_operator_restarts_crashed_child():
    g = GraphDeployment.from_dict({
        "name": "t",
        "services": {"w": {"command": [sys.executable, "-c", "pass"], "replicas": 1}},
    })
    op = LocalOperator(g, grace_s=1.0, max_restarts=50)
    try:
        await op.reconcile()
        first = op._children["w"][0]
        await first.proc.wait()  # exits immediately
        await op.reconcile()  # reaps + respawns
        assert op.status()["w"]["live"] == 1
        assert op._children["w"][0] is not first
    finally:
        await op.shutdown()


async def test_operator_crash_loop_marks_degraded():
    g = GraphDeployment.from_dict({
        "name": "t",
        "services": {"w": {"command": [sys.executable, "-c", "raise SystemExit(1)"], "replicas": 1}},
    })
    op = LocalOperator(g, max_restarts=3, restart_window_s=60.0)
    try:
        for _ in range(10):
            await op.reconcile()
            for c in op._children["w"]:
                await c.proc.wait()
            if op.status()["w"]["degraded"]:
                break
            await asyncio.sleep(0.02)
        st = op.status()["w"]
        assert st["degraded"] and st["live"] == 0  # backs off, stops respawning
    finally:
        await op.shutdown()


def test_crd_and_cr_roundtrip():
    """Graph → DynamoGraphDeployment CR → graph survives unchanged; the
    CRD schema covers every field the CR uses (ref:
    deploy/cloud/operator/api/v1alpha1)."""
    from dynamo_tpu.deploy.crd import cr_to_graph, crd_manifest, graph_to_cr, render_cluster_yaml

    graph = GraphDeployment.from_yaml(GRAPH_YAML)
    cr = graph_to_cr(graph)
    assert cr["kind"] == "DynamoGraphDeployment"
    assert cr["metadata"] == {"name": "tiny-disagg", "namespace": "prod"}
    back = cr_to_graph(cr)
    assert back.to_dict() == graph.to_dict()

    crd = crd_manifest()
    assert crd["kind"] == "CustomResourceDefinition"
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    svc_schema = schema["properties"]["spec"]["properties"]["services"]["additionalProperties"]
    for svc in cr["spec"]["services"].values():
        for key in svc:
            assert key in svc_schema["properties"], f"CR field {key} missing from CRD schema"

    docs = list(yaml.safe_load_all(render_cluster_yaml(graph)))
    assert [d["kind"] for d in docs] == ["CustomResourceDefinition", "DynamoGraphDeployment"]


async def test_kubernetes_connector_cr_scaling(tmp_path):
    """KubernetesConnector in CR mode issues the merge-patch an in-cluster
    controller reconciles; verified against a stub kubectl that records
    its argv (kubectl-apply dry-run discipline without a cluster)."""
    import json as _json

    from dynamo_tpu.planner.connectors import KubernetesConnector

    log = tmp_path / "kubectl.log"
    stub = tmp_path / "kubectl"
    stub.write_text(
        "#!/bin/sh\n"
        # printf, not echo: the first kubectl arg is "-n", which echo eats
        # as its no-newline flag.
        f"printf '%s\\n' \"$*\" >> {log}\n"
        'case "$*" in *jsonpath*) printf 3;; esac\n'
    )
    stub.chmod(0o755)

    conn = KubernetesConnector(
        namespace="prod", graph="tiny-disagg", kubectl_cmd=[str(stub)],
        extra_args=["--dry-run=client"],
    )
    await conn.set_replicas("decode", 5)
    assert await conn.get_replicas("decode") == 3
    lines = log.read_text().splitlines()
    assert "patch dynamographdeployments.dynamo.tpu.io/tiny-disagg" in lines[0]
    assert "--dry-run=client" in lines[0]
    patch = _json.loads(lines[0].split("-p ", 1)[1].rsplit(" --dry-run", 1)[0])
    assert patch == {"spec": {"services": {"decode": {"replicas": 5}}}}
    assert "get dynamographdeployments.dynamo.tpu.io/tiny-disagg" in lines[1]


async def test_kubernetes_connector_deployment_scaling(tmp_path):
    from dynamo_tpu.planner.connectors import KubernetesConnector

    log = tmp_path / "kubectl.log"
    stub = tmp_path / "kubectl"
    stub.write_text("#!/bin/sh\n" f"printf '%s\\n' \"$*\" >> {log}\n")
    stub.chmod(0o755)
    conn = KubernetesConnector(namespace="ns", kubectl_cmd=[str(stub)])
    await conn.set_replicas("decode", 2)
    assert "scale deployment/dynamo-decode --replicas=2" in log.read_text()


# --- reconciler (in-cluster operator loop) ---------------------------------

FAKE_KUBE = '''#!/usr/bin/env python3
"""Stub kube API: state in a JSON file, kubectl-shaped argv."""
import json, sys

STATE = {state_path!r}

def load():
    try:
        with open(STATE) as f:
            return json.load(f)
    except FileNotFoundError:
        return {{"dgds": {{}}, "deployments": {{}}}}

def save(s):
    with open(STATE, "w") as f:
        json.dump(s, f)

args = sys.argv[1:]
assert args[0] == "-n"
args = args[2:]  # drop -n <ns>
s = load()
if args[:2] == ["get", "dynamographdeployments"]:
    print(json.dumps({{"items": list(s["dgds"].values())}}))
elif args[:2] == ["get", "deployments"]:
    print(json.dumps({{"items": list(s["deployments"].values())}}))
elif args[:3] == ["apply", "-f", "-"]:
    man = json.loads(sys.stdin.read())
    name = man["metadata"]["name"]
    # simulate the cluster converging instantly: ready == desired
    man.setdefault("status", {{}})["readyReplicas"] = man["spec"]["replicas"]
    s["deployments"][name] = man
    save(s)
elif args[:2] == ["delete", "deployment"]:
    s["deployments"].pop(args[2], None)
    save(s)
elif args[:2] == ["patch", "dynamographdeployment"]:
    name = args[2]
    patch = json.loads(args[args.index("-p") + 1])
    s["dgds"][name].update(patch)
    save(s)
else:
    sys.exit("unhandled: " + " ".join(args))
'''


async def test_reconciler_create_scale_delete_roundtrip(tmp_path):
    """VERDICT r4 #10: the reconcile loop drives create → scale → delete
    against a stubbed kubectl, round-tripping status onto the CR."""
    import json

    from dynamo_tpu.deploy.crd import graph_to_cr
    from dynamo_tpu.deploy.reconciler import KubeReconciler
    from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec

    state = tmp_path / "kube.json"
    stub = tmp_path / "kubectl"
    stub.write_text(FAKE_KUBE.format(state_path=str(state)))
    stub.chmod(0o755)

    graph = GraphDeployment(
        name="g1",
        namespace="prod",
        services={
            "frontend": ServiceSpec(name="frontend", command=["fe"], replicas=1),
            "decode": ServiceSpec(name="decode", command=["wk"], replicas=2),
        },
    )
    state.write_text(json.dumps({"dgds": {"g1": graph_to_cr(graph)}, "deployments": {}}))

    rec = KubeReconciler(namespace="prod", kubectl_cmd=[str(stub)], image="img:1")

    # 1. CREATE: both deployments materialize; status lands on the CR.
    await rec.reconcile_once()
    s = json.loads(state.read_text())
    assert set(s["deployments"]) == {"g1-frontend", "g1-decode"}
    assert s["deployments"]["g1-decode"]["spec"]["replicas"] == 2
    st = s["dgds"]["g1"]["status"]
    assert st["services"]["decode"]["desired"] == 2
    # First pass saw no live deployments yet → not Ready.
    assert st["conditions"][0]["status"] == "False"

    # 2. Second pass observes readiness → Ready condition flips true.
    await rec.reconcile_once()
    st = json.loads(state.read_text())["dgds"]["g1"]["status"]
    assert st["services"]["decode"]["ready"] == 2
    assert st["conditions"][0]["status"] == "True"

    # 3. SCALE: bump decode 2 → 5 in the CR spec; reconcile converges.
    s = json.loads(state.read_text())
    s["dgds"]["g1"]["spec"]["services"]["decode"]["replicas"] = 5
    state.write_text(json.dumps(s))
    await rec.reconcile_once()
    s = json.loads(state.read_text())
    assert s["deployments"]["g1-decode"]["spec"]["replicas"] == 5
    assert s["dgds"]["g1"]["status"]["services"]["decode"]["desired"] == 5

    # 4. Service removed from the spec → its deployment is deleted.
    s["dgds"]["g1"]["spec"]["services"].pop("frontend")
    state.write_text(json.dumps(s))
    await rec.reconcile_once()
    s = json.loads(state.read_text())
    assert set(s["deployments"]) == {"g1-decode"}

    # 5. CR deleted → orphan sweep removes everything it managed.
    s["dgds"].pop("g1")
    state.write_text(json.dumps(s))
    await rec.reconcile_once()
    assert json.loads(state.read_text())["deployments"] == {}


async def test_reconciler_reapplies_image_env_drift(tmp_path):
    """Drift detection covers the FULL rendered manifest, not just
    spec.replicas: an operator image bump (or any env/resource change in
    the rendered manifest) re-applies even though replicas match."""
    import json

    from dynamo_tpu.deploy.crd import graph_to_cr
    from dynamo_tpu.deploy.reconciler import HASH_ANNOTATION, KubeReconciler
    from dynamo_tpu.deploy.spec import GraphDeployment, ServiceSpec

    state = tmp_path / "kube.json"
    stub = tmp_path / "kubectl"
    stub.write_text(FAKE_KUBE.format(state_path=str(state)))
    stub.chmod(0o755)

    graph = GraphDeployment(
        name="g1", namespace="prod",
        services={"decode": ServiceSpec(name="decode", command=["wk"], replicas=2)},
    )
    state.write_text(json.dumps({"dgds": {"g1": graph_to_cr(graph)}, "deployments": {}}))

    rec1 = KubeReconciler(namespace="prod", kubectl_cmd=[str(stub)], image="img:1")
    await rec1.reconcile_once()
    dep = json.loads(state.read_text())["deployments"]["g1-decode"]
    assert HASH_ANNOTATION in dep["metadata"]["annotations"]
    img1 = json.dumps(dep).count("img:1")
    assert img1 >= 1

    # Same spec, same replicas — a new operator image changes the rendered
    # manifest; the old replicas-only comparison skipped this re-apply.
    rec2 = KubeReconciler(namespace="prod", kubectl_cmd=[str(stub)], image="img:2")
    await rec2.reconcile_once()
    s = json.loads(state.read_text())
    dep2 = s["deployments"]["g1-decode"]
    assert "img:2" in json.dumps(dep2), "image drift was not re-applied"
    assert dep2["metadata"]["annotations"][HASH_ANNOTATION] != dep["metadata"]["annotations"][HASH_ANNOTATION]

    # Steady state: a third pass with the same image applies nothing new
    # (hash matches) — replicas and image unchanged.
    before = json.dumps(s["deployments"])
    await rec2.reconcile_once()
    assert json.dumps(json.loads(state.read_text())["deployments"]) == before

    # Out-of-band replica drift on the LIVE object (annotation intact) is
    # still reverted via the replicas check.
    s = json.loads(state.read_text())
    s["deployments"]["g1-decode"]["spec"]["replicas"] = 7
    state.write_text(json.dumps(s))
    await rec2.reconcile_once()
    assert json.loads(state.read_text())["deployments"]["g1-decode"]["spec"]["replicas"] == 2
