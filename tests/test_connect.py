"""Descriptor-based transfer API (dynamo.nixl_connect role) over the real
TCP data plane. Ref: lib/bindings nixl_connect/__init__.py."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.connect import Connector, Descriptor, RdmaMetadata, TransferError
from dynamo_tpu.runtime.distributed import DistributedRuntime


async def test_readable_then_read_roundtrip():
    drt = await DistributedRuntime.detached()
    try:
        conn = Connector(drt)
        src_a = np.arange(24, dtype=np.float32).reshape(4, 6)
        src_b = np.arange(10, dtype=np.int32)
        readable = await conn.create_readable(Descriptor(src_a), Descriptor(src_b))
        # Metadata travels out-of-band as JSON.
        meta = readable.metadata().to_json()

        dst_a = np.zeros((4, 6), dtype=np.float32)
        dst_b = np.zeros(10, dtype=np.int32)
        read = await conn.begin_read(meta, Descriptor(dst_a), Descriptor(dst_b))
        await read.wait_for_completion(timeout=5)
        await readable.wait_for_completion(timeout=5)

        np.testing.assert_array_equal(dst_a, src_a)
        np.testing.assert_array_equal(dst_b, src_b)
    finally:
        await drt.shutdown()


async def test_writable_then_write_roundtrip():
    drt = await DistributedRuntime.detached()
    try:
        conn = Connector(drt)
        dst = np.zeros(16, dtype=np.float64)
        writable = await conn.create_writable(Descriptor(dst))
        meta = writable.metadata().to_json()

        src = np.linspace(0, 1, 16)
        write = await conn.begin_write(meta, Descriptor(src))
        await write.wait_for_completion(timeout=5)
        await writable.wait_for_completion(timeout=5)
        np.testing.assert_array_equal(dst, src)
    finally:
        await drt.shutdown()


async def test_jax_descriptor_roundtrip():
    drt = await DistributedRuntime.detached()
    try:
        conn = Connector(drt)
        src = jnp.arange(32, dtype=jnp.float32).reshape(8, 4) * 0.5
        readable = await conn.create_readable(Descriptor(src))
        dst = np.zeros((8, 4), dtype=np.float32)
        d = Descriptor(dst)
        read = await conn.begin_read(readable.metadata(), d)
        await read.wait_for_completion(timeout=5)
        back = d.to_jax()
        assert isinstance(back, jax.Array)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(src))
    finally:
        await drt.shutdown()


async def test_shape_mismatch_is_error():
    drt = await DistributedRuntime.detached()
    try:
        conn = Connector(drt)
        readable = await conn.create_readable(Descriptor(np.zeros(8, dtype=np.float32)))
        wrong = np.zeros(9, dtype=np.float32)
        read = await conn.begin_read(readable.metadata(), Descriptor(wrong))
        with pytest.raises(TransferError):
            await read.wait_for_completion(timeout=5)
        await readable.cancel()
    finally:
        await drt.shutdown()


async def test_metadata_json_roundtrip():
    m = RdmaMetadata("writable", "abc", [{"shape": [2], "dtype": "float32"}], conn={"host": "h"})
    m2 = RdmaMetadata.from_json(m.to_json())
    assert m2.kind == "writable" and m2.nonce == "abc" and m2.conn == {"host": "h"}


async def test_readable_serves_multiple_reads():
    drt = await DistributedRuntime.detached()
    try:
        conn = Connector(drt)
        src = np.arange(6, dtype=np.int64)
        readable = await conn.create_readable(Descriptor(src), remaining_reads=2)
        outs = [np.zeros(6, dtype=np.int64) for _ in range(2)]
        for o in outs:
            r = await conn.begin_read(readable.metadata(), Descriptor(o))
            await r.wait_for_completion(timeout=5)
        await readable.wait_for_completion(timeout=5)
        for o in outs:
            np.testing.assert_array_equal(o, src)
    finally:
        await drt.shutdown()
