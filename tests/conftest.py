"""Test config: force JAX onto a virtual 8-device CPU mesh so sharding tests
run anywhere (SURVEY.md §4 — the reference runs distributed tests against
mockers + local etcd/NATS; we run against in-memory control plane + CPU mesh).

Must set env before jax initializes a backend.
"""

import os

# Force CPU even if the session env points at a real TPU (axon): tests must
# be hermetic and the single real chip is reserved for benchmarking. The env
# var alone is NOT enough — the axon PJRT plugin overrides JAX_PLATFORMS, so
# we also set the config flag right after import.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("DYN_LOG", "WARNING")

import jax

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on the CPU backend"
assert len(jax.devices()) == 8, "expected 8 virtual CPU devices"

import asyncio
import functools

import pytest


def pytest_collection_modifyitems(config, items):
    """Run coroutine test functions via asyncio.run (no pytest-asyncio here)."""
    for item in items:
        if asyncio.iscoroutinefunction(getattr(item, "function", None)):
            item.obj = _sync(item.function)


def _sync(fn):
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(fn(*args, **kwargs))

    return wrapper
