"""Unit tests for the KV store control plane (lease + watch semantics the
whole discovery stack depends on)."""

import asyncio

import pytest

from dynamo_tpu.runtime.transports.kvstore import (
    EventType,
    KeyExists,
    MemKvStore,
)


async def test_put_get_delete():
    store = MemKvStore()
    await store.put("a/b", b"1")
    entry = await store.get("a/b")
    assert entry is not None and entry.value == b"1"
    assert await store.delete("a/b")
    assert await store.get("a/b") is None
    assert not await store.delete("a/b")
    await store.close()


async def test_get_prefix_sorted():
    store = MemKvStore()
    await store.put("x/2", b"b")
    await store.put("x/1", b"a")
    await store.put("y/1", b"c")
    entries = await store.get_prefix("x/")
    assert [e.key for e in entries] == ["x/1", "x/2"]
    await store.close()


async def test_create_only():
    store = MemKvStore()
    await store.put("k", b"1", create_only=True)
    with pytest.raises(KeyExists):
        await store.put("k", b"2", create_only=True)
    await store.close()


async def test_watch_snapshot_then_deltas():
    store = MemKvStore()
    await store.put("w/1", b"a")
    watch = await store.watch_prefix("w/")
    events = []

    async def consume():
        async for ev in watch:
            events.append(ev)
            if len(events) == 3:
                return

    task = asyncio.create_task(consume())
    await asyncio.sleep(0.01)
    await store.put("w/2", b"b")
    await store.delete("w/1")
    await asyncio.wait_for(task, 2)
    assert (events[0].type, events[0].key) == (EventType.PUT, "w/1")
    assert (events[1].type, events[1].key) == (EventType.PUT, "w/2")
    assert (events[2].type, events[2].key) == (EventType.DELETE, "w/1")
    await watch.cancel()
    await store.close()


async def test_lease_expiry_deletes_keys_and_notifies():
    store = MemKvStore(reaper_interval_s=0.05)
    lease = await store.grant_lease(ttl_s=0.15)
    await store.put("inst/a", b"x", lease_id=lease.id)
    watch = await store.watch_prefix("inst/")
    # consume snapshot PUT
    it = watch._gen()
    first = await asyncio.wait_for(it.__anext__(), 2)
    assert first.type == EventType.PUT
    # no keepalive → reaper deletes the key
    ev = await asyncio.wait_for(it.__anext__(), 2)
    assert ev.type == EventType.DELETE and ev.key == "inst/a"
    assert await store.get("inst/a") is None
    await watch.cancel()
    await store.close()


async def test_lease_keepalive_preserves_keys():
    store = MemKvStore(reaper_interval_s=0.05)
    lease = await store.grant_lease(ttl_s=0.2)
    await store.put("inst/b", b"x", lease_id=lease.id)
    for _ in range(5):
        await asyncio.sleep(0.1)
        await store.keep_alive(lease.id)
    assert await store.get("inst/b") is not None
    await lease.revoke()
    assert await store.get("inst/b") is None
    await store.close()


async def test_shared_key_rebinds_to_newest_lease():
    """A key re-put under a different lease (two workers registering the
    same model entry) must belong to the NEWEST lease only: revoking or
    draining the old worker cannot delete a key the survivor still backs."""
    store = MemKvStore()
    a = await store.grant_lease(10.0)
    b = await store.grant_lease(10.0)
    await store.put("models/ns/c/e/m", b"worker-a", lease_id=a.id)
    await store.put("models/ns/c/e/m", b"worker-b", lease_id=b.id)
    await store.revoke_lease(a.id)
    entry = await store.get("models/ns/c/e/m")
    assert entry is not None and entry.value == b"worker-b"
    await store.revoke_lease(b.id)
    assert await store.get("models/ns/c/e/m") is None
    await store.close()
