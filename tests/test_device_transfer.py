"""Device-native KV transfer plane (the NIXL replacement).

- stacked device gather/scatter + cache→cache copy primitives
- in-process disagg e2e over the device handoff (token parity)
- cross-process one-sided pull via jax.experimental.transfer (two
  subprocesses, CPU backend)
Ref: nixl_connect/__init__.py:501-1417; SURVEY.md §7 hard part (a).
"""

import asyncio
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.llm.block_manager.transfer import (
    copy_blocks_between,
    gather_blocks,
    gather_blocks_device,
    scatter_blocks_device,
)


def filled_cache(cfg, num_blocks, seed):
    cache = KvCacheArrays.create(cfg, num_blocks, dtype=jnp.float32)
    shape = cache.k.shape
    cache.k = jax.random.normal(jax.random.PRNGKey(seed), shape, dtype=jnp.float32)
    cache.v = jax.random.normal(jax.random.PRNGKey(seed + 1), shape, dtype=jnp.float32)
    return cache


def test_gather_scatter_device_roundtrip():
    cfg = get_config("tiny")
    src = filled_cache(cfg, 16, 0)
    dst = KvCacheArrays.create(cfg, 16, dtype=jnp.float32)

    bids = [3, 7, 2]
    k_stack, v_stack = gather_blocks_device(src, bids)
    assert k_stack.shape == (cfg.num_layers, 3, cfg.block_size, cfg.num_kv_heads, cfg.head_dim)

    dst_bids = [1, 4, 9]
    scatter_blocks_device(dst, dst_bids, k_stack, v_stack)
    for sb, db in zip(bids, dst_bids):
        ks, _ = gather_blocks(src, sb)
        kd, _ = gather_blocks(dst, db)
        np.testing.assert_array_equal(ks, kd)


def test_copy_blocks_between_caches():
    cfg = get_config("tiny")
    src = filled_cache(cfg, 16, 2)
    dst = KvCacheArrays.create(cfg, 32, dtype=jnp.float32)
    copy_blocks_between(src, [5, 6], dst, [20, 21])
    k5, v5 = gather_blocks(src, 5)
    k20, v20 = gather_blocks(dst, 20)
    np.testing.assert_array_equal(k5, k20)
    np.testing.assert_array_equal(v5, v20)


async def test_disagg_device_handoff_matches_aggregated():
    """Full disagg flow with kv_transfer='device' (in-process direct
    handoff): output must be token-identical to aggregated serving."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.disagg import DisaggDecodeHandler, KvExportService
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    def build_engine():
        return TpuEngine.build(
            EngineArgs(
                model="tiny", dtype="float32", seed=7,
                scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64],
                                          decode_buckets=[1, 2, 4, 8],
                                          enable_prefix_caching=False),
            )
        )

    async def collect(engine_like, request):
        out, fin = [], None
        async for frame in engine_like.generate(request, Context()):
            data = frame.data if hasattr(frame, "data") else frame
            if data:
                out.extend(data.get("token_ids") or [])
                fin = data.get("finish_reason") or fin
        return out, fin

    req = {
        "token_ids": list(range(20, 60)),
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": 6},
    }

    drt = await DistributedRuntime.detached()
    try:
        prefill_engine = build_engine()
        decode_engine = build_engine()
        ep = drt.namespace("dxd").component("prefill").endpoint("generate")
        handle = await ep.serve_endpoint(prefill_engine.generate, stats_handler=prefill_engine.stats_handler)
        kvx = KvExportService(drt, prefill_engine, handle.instance)
        await kvx.start()

        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        handler = DisaggDecodeHandler(drt, decode_engine, client, kv_transfer="device")

        ref_engine = build_engine()
        ref, _ = await collect(ref_engine, req)
        await ref_engine.stop()

        out, fin = await collect(handler, req)
        assert out == ref, f"device disagg {out} != aggregated {ref}"
        assert fin == "length"
        assert handler.remote_prefills == 1
        assert prefill_engine.scheduler.allocator.num_active == 0
        assert not prefill_engine.scheduler._pending_exports

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


PRODUCER = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import sys, time
    from dynamo_tpu.llm.block_manager.device_transfer import DeviceTransferPlane

    plane = DeviceTransferPlane()
    x = jnp.arange(65536, dtype=jnp.float32).reshape(64, 1024)
    meta = plane.offer("req-x", [x])
    import json
    print(json.dumps(meta), flush=True)
    time.sleep(15)
""")

CONSUMER = textwrap.dedent("""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import json, sys
    import numpy as np
    from dynamo_tpu.llm.block_manager.device_transfer import DeviceTransferPlane

    meta = json.loads(sys.argv[1])
    plane = DeviceTransferPlane()
    out = plane.pull(meta)
    expect = np.arange(65536, dtype=np.float32).reshape(64, 1024)
    assert (np.asarray(out[0]) == expect).all(), "payload mismatch"
    print("PULL_OK", flush=True)
""")


def test_cross_process_device_pull():
    """Two processes: producer offers device buffers, consumer pulls them
    one-sided through the transfer plane (the NIXL wire)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    prod = subprocess.Popen(
        [sys.executable, "-c", PRODUCER], stdout=subprocess.PIPE, env=env, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        meta_line = prod.stdout.readline().strip()
        assert meta_line.startswith("{"), f"producer output: {meta_line!r}"
        cons = subprocess.run(
            [sys.executable, "-c", CONSUMER, meta_line],
            capture_output=True, text=True, timeout=60, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert "PULL_OK" in cons.stdout, f"consumer failed: {cons.stdout}\n{cons.stderr}"
    finally:
        prod.kill()
        prod.wait()
