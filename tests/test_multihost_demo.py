"""Two-process multi-host serving demo must complete (tools/demo_multihost.py):
real OS processes, control-plane rendezvous, jax multi-controller runtime,
one dp×tp mesh spanning both, identical SPMD step results. This is the
recorded-gate version of what engine/multihost.py promises (ref:
MultiNodeConfig engines.rs:28)."""

import json
import os
import subprocess
import sys


def test_two_process_demo_completes():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items() if k not in ("DYN_CONTROL_PLANE",)}
    out = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "demo_multihost.py")],
        capture_output=True, text=True, timeout=300, cwd=repo, env=env,
    )
    assert out.returncode == 0, out.stdout[-800:] + out.stderr[-300:]
    artifact = json.loads(out.stdout.strip().splitlines()[-1])
    assert artifact["ok"] and artifact["spmd_results_identical"]
    assert all(w["global_devices"] == 8 for w in artifact["workers"])
    assert {w["process"] for w in artifact["workers"]} == {0, 1}
