"""End-to-end request tracing: one HTTP request through the demo stack
(frontend → push_router → worker wire path → TpuEngine scheduler) must yield
ONE trace id spanning frontend/worker/scheduler records in the JSONL export,
plus a valid Chrome-trace conversion; and the engine flight recorder's XLA
compile tracker must report 0 post-warmup compiles in steady state and >0
with warmup disabled."""

import asyncio
import json
import os
import subprocess
import sys

import aiohttp
import pytest

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.entrypoint import build_routed_pipeline, register_llm
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.http.service import TRACE_ID_HEADER, HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.push_router import PushRouter
from dynamo_tpu.runtime.tracing import (
    chrome_trace,
    configure_tracing,
    get_tracer,
    read_trace_file,
)

MODEL = "tiny-traced"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def trace_file(tmp_path):
    """Point the process tracer at a fresh JSONL file; restore the disabled
    tracer afterwards so other tests see zero overhead."""
    path = str(tmp_path / "trace.jsonl")
    configure_tracing(path=path, sample=1.0, service="test")
    yield path
    configure_tracing(path=None, sample=0.0)


def tiny_engine(warmup_ctx=0) -> TpuEngine:
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            eos_token_ids=[0],
            scheduler=SchedulerConfig(
                num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4]
            ),
            warmup_ctx=warmup_ctx,
        )
    )


async def test_single_trace_through_demo_stack(trace_file, tmp_path):
    """frontend → router (wire path) → worker → scheduler: every record in
    the export carries the caller's trace id."""
    drt = await DistributedRuntime.detached()
    engine = tiny_engine()
    service = None
    try:
        ep = drt.namespace("tracetest").component("backend").endpoint("generate")
        card = ModelDeploymentCard(name=MODEL, model_type="chat")
        handle, _ = await register_llm(drt, ep, engine, card, stats_handler=engine.stats_handler)
        # Force the real wire path (pub/sub + TCP call-home): the in-process
        # fast path would skip the worker ingress span.
        drt.local_engines.pop(handle.instance.instance_id)

        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        manager = ModelManager()
        pipeline = build_routed_pipeline(ByteTokenizer(), PushRouter(client), card)
        manager.add_model("chat", MODEL, pipeline)
        service = HttpService(manager, host="127.0.0.1", port=0)
        await service.start()

        trace_id = "ab" * 16
        headers = {"traceparent": f"00-{trace_id}-{'cd' * 8}-01"}
        body = {
            "model": MODEL,
            "messages": [{"role": "user", "content": "trace me"}],
            "max_tokens": 4,
            "temperature": 0,
        }
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json=body, headers=headers,
            ) as r:
                assert r.status == 200, await r.text()
                # The trace id is echoed on the response.
                assert r.headers[TRACE_ID_HEADER] == trace_id
                await r.json()
    finally:
        if service is not None:
            await service.stop()
        await engine.stop()
        await drt.shutdown()

    get_tracer().flush()
    records = read_trace_file(trace_file)
    assert records, "no trace records exported"
    assert {rec["trace_id"] for rec in records} == {trace_id}, "trace id fragmented"

    by_service = {}
    for rec in records:
        by_service.setdefault(rec["service"], set()).add(rec["name"])
    assert "http_request" in by_service.get("frontend", set())
    assert "route" in by_service.get("frontend", set())
    assert "worker_handle" in by_service.get("worker", set())
    sched = by_service.get("scheduler", set())
    for name in ("queued", "admitted", "first_token", "finish"):
        assert name in sched, f"missing scheduler event {name}: {sched}"

    # Parenting: the worker span's parent is a frontend span of this trace.
    spans = {r["span_id"]: r for r in records if r["kind"] == "span"}
    worker = next(r for r in records if r["name"] == "worker_handle")
    assert worker["parent_id"] in spans
    assert spans[worker["parent_id"]]["service"] == "frontend"

    # Chrome-trace conversion is structurally valid and covers all records.
    ct = chrome_trace(records)
    assert ct["traceEvents"]
    phases = {e["ph"] for e in ct["traceEvents"]}
    assert "X" in phases and "i" in phases
    json.dumps(ct)  # serializable

    # The CLI renders both views without error.
    out = str(tmp_path / "chrome.json")
    for argv in (
        [sys.executable, os.path.join(REPO, "tools", "trace_view.py"), trace_file],
        [sys.executable, os.path.join(REPO, "tools", "trace_view.py"), trace_file,
         "-t", trace_id],
        [sys.executable, os.path.join(REPO, "tools", "trace_view.py"), trace_file,
         "--chrome", out],
    ):
        proc = subprocess.run(argv, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
    assert json.load(open(out))["traceEvents"]


async def test_unsampled_requests_export_nothing(trace_file):
    """sample=0 keeps ids flowing (header echo) but exports no records."""
    configure_tracing(path=trace_file, sample=0.0)
    engine = tiny_engine()
    try:
        req = {"token_ids": list(range(12)), "sampling_options": {"temperature": 0},
               "stop_conditions": {"max_tokens": 3}}
        async for _ in engine.generate(req, Context()):
            pass
    finally:
        await engine.stop()
    get_tracer().flush()
    assert not os.path.exists(trace_file) or not read_trace_file(trace_file)


def test_deterministic_sampling_decision(trace_file):
    """The keep/drop decision is a pure function of the trace id — the
    property that makes one request one trace across processes."""
    tracer = configure_tracing(path=trace_file, sample=0.5)
    ids = [f"{i:032x}" for i in range(1, 200)]
    first = [tracer.sampled(t) for t in ids]
    assert [tracer.sampled(t) for t in ids] == first
    assert any(first) and not all(first), "0.5 sampling should split the ids"


async def test_compile_tracker_steady_state_vs_cold(trace_file):
    """Warmed engine: serving traffic compiles nothing new. Cold engine:
    the same traffic shows up in compiles_after_warmup_total — PR 1's
    mid-traffic compile killer, now a counter."""

    async def run_traffic(engine):
        for start in (0, 40):  # two requests, same shapes second time
            req = {"token_ids": list(range(start, start + 20)),
                   "sampling_options": {"temperature": 0},
                   "stop_conditions": {"max_tokens": 4}}
            async for _ in engine.generate(req, Context()):
                pass

    warmed = tiny_engine(warmup_ctx=64)
    try:
        await run_traffic(warmed)
        stats = warmed.stats_handler()
        assert stats["compiles_after_warmup_total"] == 0, (
            f"steady state compiled: {warmed.scheduler.flight.post_warmup_keys}"
        )
        assert stats["compiles_total"] > 0
        assert stats["step_decode_steps_total"] > 0
        assert stats["step_prefill_steps_total"] > 0
    finally:
        await warmed.stop()

    cold = tiny_engine(warmup_ctx=0)
    try:
        await run_traffic(cold)
        stats = cold.stats_handler()
        assert stats["compiles_after_warmup_total"] > 0
        assert cold.scheduler.flight.post_warmup_keys  # shape keys recorded
    finally:
        await cold.stop()
