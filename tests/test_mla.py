"""MLA (DeepSeek-style latent attention) family tests: decode-vs-prefill
consistency over the paged latent cache, engine e2e, cache sizing."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import get_module, mla
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.runtime.engine import Context

CFG = get_config("tiny-mla")


def test_dispatch():
    assert get_module(CFG) is mla
    assert get_module(get_config("tiny")).__name__.endswith("llama")


def test_latent_cache_shape():
    cache = KvCacheArrays.create(CFG, num_blocks=8, dtype=jnp.float32)
    # One latent row per token: kv_lora_rank + rope dim, single "head".
    assert cache.k.shape == (2, 8, 16, 1, 40)
    assert cache.v.shape == (2, 1, 1, 1, 1)


def test_decode_matches_prefill_logits():
    """Token t+1 logits from decode (after prefilling t tokens) must match
    prefilling t+1 tokens directly — same latent cache contract."""
    key = jax.random.PRNGKey(0)
    params = mla.init_params(CFG, key, dtype=jnp.float32)
    prompt = list(range(30, 45))
    T = len(prompt)
    bucket = 16
    n_blocks = 4
    cache = KvCacheArrays.create(CFG, num_blocks=8, dtype=jnp.float32)
    table = jnp.arange(1, 1 + n_blocks, dtype=jnp.int32)

    padded = jnp.zeros((bucket,), dtype=jnp.int32).at[:T].set(jnp.asarray(prompt))
    logits_p, k1, v1 = mla.prefill(
        params, CFG, cache.k, cache.v, padded, jnp.int32(T), jnp.int32(0), table
    )

    # Decode one token on top of the prefilled cache.
    next_tok = int(jnp.argmax(logits_p))
    logits_d, k2, _ = mla.decode(
        params, CFG, k1, v1,
        jnp.asarray([next_tok], dtype=jnp.int32),
        jnp.asarray([T], dtype=jnp.int32),
        table[None, :],
        jnp.ones((1,), dtype=bool),
    )

    # Reference: prefill the full T+1 sequence in a fresh cache.
    cache2 = KvCacheArrays.create(CFG, num_blocks=8, dtype=jnp.float32)
    full = prompt + [next_tok]
    padded2 = jnp.zeros((bucket,), dtype=jnp.int32).at[: T + 1].set(jnp.asarray(full))
    logits_ref, _, _ = mla.prefill(
        params, CFG, cache2.k, cache2.v, padded2, jnp.int32(T + 1), jnp.int32(0), table
    )
    np.testing.assert_allclose(np.asarray(logits_d[0]), np.asarray(logits_ref), rtol=2e-4, atol=2e-4)


def test_mla_engine_e2e():
    async def run():
        engine = TpuEngine.build(
            EngineArgs(
                model="tiny-mla",
                dtype="float32",
                scheduler=SchedulerConfig(
                    num_blocks=32, max_running=4, prefill_buckets=[16, 32], decode_buckets=[1, 2, 4]
                ),
            )
        )
        try:
            out = []
            async for frame in engine.generate(
                {"token_ids": list(range(10, 28)),
                 "sampling_options": {"temperature": 0.0},
                 "stop_conditions": {"max_tokens": 6}},
                Context(),
            ):
                out.extend(frame["token_ids"])
            assert len(out) == 6
            # Greedy determinism across a second request (prefix cache hit).
            out2 = []
            async for frame in engine.generate(
                {"token_ids": list(range(10, 28)),
                 "sampling_options": {"temperature": 0.0},
                 "stop_conditions": {"max_tokens": 6}},
                Context(),
            ):
                out2.extend(frame["token_ids"])
            assert out == out2
        finally:
            await engine.stop()

    asyncio.run(run())


def test_presets_construct():
    for name in ("deepseek-v2-lite", "deepseek-v3", "qwen2.5-7b", "mistral-7b"):
        cfg = get_config(name)
        assert cfg.architecture in ("llama", "mla")
        if cfg.architecture == "mla":
            assert cfg.kv_lora_rank > 0 and cfg.v_head_dim > 0
