"""Tests for the built-in TCP control plane: the same discovery/routing flows
as test_component.py but across the real broker protocol, including a true
multi-process worker (the reference's equivalent is tests against live
etcd+NATS, SURVEY.md §4 item 2)."""

import asyncio
import os
import signal
import subprocess
import sys
import textwrap

import pytest

from dynamo_tpu.runtime import DistributedRuntime, PushRouter
from dynamo_tpu.runtime.runtime import Runtime
from dynamo_tpu.runtime.transports.tcp_control import (
    ControlPlaneServer,
    TcpKvStore,
    TcpPubSub,
    connect_control_plane,
)


async def _drt_pair():
    """Broker + two connected DistributedRuntimes (worker + client)."""
    server = ControlPlaneServer(host="127.0.0.1", port=0)
    await server.start()
    drts = []
    for _ in range(2):
        conn = await connect_control_plane(f"127.0.0.1:{server.port}")
        drt = DistributedRuntime(runtime=Runtime(), store=TcpKvStore(conn), bus=TcpPubSub(conn))
        await drt.start()
        drts.append(drt)
    return server, drts[0], drts[1]


async def test_kv_roundtrip_over_tcp():
    server = ControlPlaneServer(host="127.0.0.1", port=0)
    await server.start()
    conn = await connect_control_plane(f"127.0.0.1:{server.port}")
    store = TcpKvStore(conn)
    await store.put("a/1", b"x")
    assert (await store.get("a/1")).value == b"x"
    assert [e.key for e in await store.get_prefix("a/")] == ["a/1"]
    snapshot, watch = await store.get_and_watch_prefix("a/")
    assert [e.key for e in snapshot] == ["a/1"]
    await store.put("a/2", b"y")
    ev = await asyncio.wait_for(watch._gen().__anext__(), 2)
    assert ev.key == "a/2"
    await watch.cancel()
    await conn.close()
    await server.close()


async def test_pubsub_and_stream_over_tcp():
    server = ControlPlaneServer(host="127.0.0.1", port=0)
    await server.start()
    conn = await connect_control_plane(f"127.0.0.1:{server.port}")
    bus = TcpPubSub(conn)
    sub = await bus.subscribe("x.*")
    await bus.publish("x.y", b"m1")
    msg = await asyncio.wait_for(sub.next(), 2)
    assert msg.data == b"m1"

    stream = await bus.stream("events")
    await stream.publish("events", b"e1")
    await stream.publish("events", b"e2")
    batch = await stream.fetch(1)
    assert [m.data for m in batch] == [b"e1", b"e2"]

    obj = await bus.object_store("bucket")
    await obj.put("s", b"blob")
    assert await obj.get("s") == b"blob"
    await bus.close()
    await server.close()


async def test_cross_runtime_routing_over_broker():
    """Two runtimes (worker + frontend) connected only through the broker +
    TCP call-home data plane."""
    server, worker_drt, client_drt = await _drt_pair()
    try:
        ep_w = worker_drt.namespace("t").component("c").endpoint("gen")

        async def handler(request, context):
            for i in range(3):
                yield {"i": i}

        await ep_w.serve_endpoint(handler)
        ep_c = client_drt.namespace("t").component("c").endpoint("gen")
        client = await ep_c.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)
        out = [a.data["i"] async for a in router.generate({})]
        assert out == [0, 1, 2]
    finally:
        await worker_drt.shutdown()
        await client_drt.shutdown()
        await server.close()


async def test_worker_death_revokes_leases():
    """Dropping the worker's broker connection revokes its leases: the
    client's watch prunes the instance (etcd session-loss semantics)."""
    server, worker_drt, client_drt = await _drt_pair()
    try:
        ep_w = worker_drt.namespace("t").component("c").endpoint("gen")

        async def handler(request, context):
            yield {}

        await ep_w.serve_endpoint(handler)
        ep_c = client_drt.namespace("t").component("c").endpoint("gen")
        client = await ep_c.client()
        await client.wait_for_instances(1, timeout=5)

        # Simulate worker crash: kill its broker connection abruptly.
        await worker_drt.store.conn.close()
        for _ in range(100):
            if not client.instances:
                break
            await asyncio.sleep(0.05)
        assert not client.instances
    finally:
        await client_drt.shutdown()
        await server.close()


@pytest.mark.e2e
async def test_multiprocess_worker():
    """Full multi-process slice: broker in-process, worker in a subprocess,
    requests routed across real sockets."""
    server = ControlPlaneServer(host="127.0.0.1", port=0)
    await server.start()

    worker_code = textwrap.dedent(
        f"""
        import asyncio, os
        os.environ["DYN_CONTROL_PLANE"] = "tcp"
        os.environ["DYN_CONTROL_PLANE_ADDRESS"] = "127.0.0.1:{server.port}"
        from dynamo_tpu.runtime import DistributedRuntime

        async def handler(request, context):
            yield {{"echo": request["msg"], "pid": os.getpid()}}

        async def main():
            drt = await DistributedRuntime.from_settings()
            ep = drt.namespace("mp").component("c").endpoint("gen")
            await ep.serve_endpoint(handler)
            print("READY", flush=True)
            await asyncio.sleep(60)

        asyncio.run(main())
        """
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", worker_code], stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env, text=True
    )
    try:
        line = await asyncio.wait_for(asyncio.to_thread(proc.stdout.readline), 30)
        assert "READY" in line

        conn = await connect_control_plane(f"127.0.0.1:{server.port}")
        drt = DistributedRuntime(runtime=Runtime(), store=TcpKvStore(conn), bus=TcpPubSub(conn))
        await drt.start()
        ep = drt.namespace("mp").component("c").endpoint("gen")
        client = await ep.client()
        await client.wait_for_instances(1, timeout=10)
        router = PushRouter(client)
        out = [a.data async for a in router.generate({"msg": "hello"})]
        assert out[0]["echo"] == "hello"
        assert out[0]["pid"] == proc.pid
        await drt.shutdown()
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        await server.close()
