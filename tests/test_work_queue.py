"""Durable work queue (NatsQueue role) + prefill-first disaggregation.

Ref: _core.pyi:894 NatsQueue; trtllm handler_base.py:42-55
DisaggregationStrategy::prefill_first.
"""

import asyncio

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.work_queue import WorkQueue


async def test_enqueue_dequeue_ack():
    drt = await DistributedRuntime.detached()
    try:
        q = WorkQueue(drt.store, drt.bus, "jobs")
        await q.enqueue(b"a")
        await q.enqueue(b"b")
        assert await q.depth() == 2
        item = await q.dequeue(timeout=1)
        assert item.data == b"a"
        assert await q.depth() == 1  # claimed item no longer available
        await item.ack()
        item2 = await q.dequeue(timeout=1)
        assert item2.data == b"b"
        await item2.ack()
        assert await q.depth() == 0
        assert await q.dequeue(timeout=0.1) is None
    finally:
        await drt.shutdown()


async def test_competing_consumers_each_item_once():
    drt = await DistributedRuntime.detached()
    try:
        producer = WorkQueue(drt.store, drt.bus, "jobs")
        for i in range(20):
            await producer.enqueue(str(i).encode())

        seen = []

        async def consume(name):
            q = WorkQueue(drt.store, drt.bus, "jobs")
            while True:
                item = await q.dequeue(timeout=0.3)
                if item is None:
                    return
                seen.append((name, item.data))
                await item.ack()

        await asyncio.gather(consume("c1"), consume("c2"), consume("c3"))
        payloads = sorted(int(d) for _, d in seen)
        assert payloads == list(range(20))  # exactly-once across consumers
    finally:
        await drt.shutdown()


async def test_dead_consumer_claim_redelivered():
    drt = await DistributedRuntime.detached()
    try:
        drt.store._reaper_interval_s = 0.05
        q = WorkQueue(drt.store, drt.bus, "jobs")
        await q.enqueue(b"task")

        lease = await drt.store.grant_lease(0.15)
        dead = WorkQueue(drt.store, drt.bus, "jobs", lease_id=lease.id)
        item = await dead.dequeue(timeout=1)
        assert item is not None and item.data == b"task"
        # Consumer dies without ack: its lease lapses, claim evaporates.
        other = WorkQueue(drt.store, drt.bus, "jobs")
        redelivered = await other.dequeue(timeout=2)
        assert redelivered is not None and redelivered.data == b"task"
        await redelivered.ack()
    finally:
        await drt.shutdown()


async def test_acked_prefix_purged():
    drt = await DistributedRuntime.detached()
    try:
        q = WorkQueue(drt.store, drt.bus, "jobs")
        for i in range(5):
            await q.enqueue(str(i).encode())
        for _ in range(5):
            item = await q.dequeue(timeout=1)
            await item.ack()
        stream = await drt.bus.stream("wq_jobs")
        assert stream.first_seq == 6  # fully-acked prefix dropped
        assert await drt.store.get_prefix("wq/jobs/done/") == []
    finally:
        await drt.shutdown()
