"""Metrics hygiene: instantiate every registry the serving roles create,
render them, and assert the exposition obeys the conventions Prometheus
tooling relies on — unique family names, counters ending in ``_total``,
histograms with explicitly declared (non-default) buckets — plus the
regression test for the ``_get_or_create`` label-mismatch trap."""

import re

import pytest

from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.metrics_aggregator import COUNTER_KEYS, GAUGE_KEYS, MetricsAggregator
from dynamo_tpu.runtime.metrics import MetricsRegistry

# prometheus_client's implicit default buckets: a histogram rendering these
# exact bounds almost certainly forgot to declare LLM-scale buckets.
_DEFAULT_LE = {
    "0.005", "0.01", "0.025", "0.05", "0.075", "0.1", "0.25", "0.5",
    "0.75", "1.0", "2.5", "5.0", "7.5", "10.0", "+Inf",
}


def parse_families(text: str):
    """{family_name: {"type": t, "samples": [...], "le": set()}} from the
    Prometheus text exposition."""
    fams = {}
    for line in text.splitlines():
        m = re.match(r"# TYPE (\S+) (\S+)", line)
        if m:
            fams[m.group(1)] = {"type": m.group(2), "samples": [], "le": set()}
            continue
        if line.startswith("#") or not line.strip():
            continue
        name = line.split("{")[0].split(" ")[0]
        fam_name = next((f for f in fams if name == f or name.startswith(f + "_")), None)
        if fam_name:
            fams[fam_name]["samples"].append(name)
            le = re.search(r'le="([^"]+)"', line)
            if le:
                fams[fam_name]["le"].add(le.group(1))
    return fams


def frontend_registry() -> MetricsRegistry:
    """HttpService's registry with every metric factory touched (the way a
    live frontend would after serving traffic)."""
    from dynamo_tpu.runtime.telemetry import SloConfig

    service = HttpService(
        ModelManager(), host="127.0.0.1", port=0,
        slo=SloConfig(ttft_ms=100.0, tpot_ms=20.0),
    )
    model = "hygiene-model"
    service._m_requests(model, "200").inc()
    service._m_inflight(model).set(1)
    service._m_ttft(model).observe(0.1)
    service._m_itl(model).observe(0.01)
    service._m_duration(model).observe(0.5)
    service._m_queue(model).observe(0.02)
    service._m_output_tokens(model).inc(10)
    service._m_input_tokens(model).inc(20)
    # SLA telemetry path: one attained + one violated request through the
    # real recording helper (digest families + SLO/goodput counters/gauges).
    import time

    t0 = time.monotonic()
    service._record_request_telemetry(model, t0 - 0.05, t0 - 0.04, t0, 8)
    service._record_request_telemetry(model, t0 - 5.0, t0 - 0.1, t0, 8)
    return service.metrics


def aggregator_registry() -> MetricsRegistry:
    """MetricsAggregator's registry fed one full scrape covering every
    gauge and counter key a worker can report, plus a digest payload and a
    tenant-ledger wire so the fleet digest re-exports and the labeled
    per-tenant families render too."""
    from dynamo_tpu.metrics_aggregator import DIGEST_KEYS
    from dynamo_tpu.runtime.ledger import RequestBill, TenantLedger
    from dynamo_tpu.runtime.telemetry import SloConfig, Telemetry

    telem = Telemetry()
    for name in DIGEST_KEYS:
        telem.observe(name, 0.1)
    ledger = TenantLedger(top_k=4, slo=SloConfig(ttft_ms=100.0, tpot_ms=10.0))
    ledger.record(RequestBill(tenant="hygiene", prefill_device_s=0.1,
                              decode_device_s=0.2, kv_block_s=1.0, queue_s=0.01,
                              output_tokens=8, ttft_s=0.05, tpot_s=0.2))
    agg = MetricsAggregator(drt=None, namespace="ns", component="backend", endpoint="generate")
    stats = {0xA: {**{key: 1.0 for key in GAUGE_KEYS + COUNTER_KEYS},
                   "digests": telem.to_wire(),
                   "tenant_ledger": ledger.to_wire()}}
    agg.export_stats(stats)
    agg.export_stats(stats)  # second scrape exercises the delta path
    return agg.registry


@pytest.mark.parametrize("make_registry", [frontend_registry, aggregator_registry],
                         ids=["frontend", "aggregator"])
def test_registry_hygiene(make_registry):
    registry = make_registry()
    text = registry.render().decode()
    fams = parse_families(text)
    assert fams, "registry rendered no metric families"

    # No duplicate family names (TYPE declared once per family).
    names = re.findall(r"# TYPE (\S+) ", text)
    assert len(names) == len(set(names)), f"duplicate families: {sorted(names)}"

    for name, fam in fams.items():
        # Counters must expose rate()-able *_total samples.
        if fam["type"] == "counter":
            totals = [s for s in fam["samples"] if s.endswith("_total")]
            assert totals, f"counter {name} renders no _total sample"
        # Histograms must declare buckets explicitly — the prometheus_client
        # defaults are request-latency-shaped for generic web apps, not for
        # TTFT/ITL/step-time scales.
        if fam["type"] == "histogram":
            assert fam["le"], f"histogram {name} has no buckets"
            assert fam["le"] != _DEFAULT_LE, (
                f"histogram {name} uses prometheus_client default buckets; "
                "declare buckets= explicitly"
            )


def test_monotonic_worker_stats_export_as_counters():
    """Satellite regression: ``*_total`` worker stats must not be exported
    as Gauges (breaks PromQL rate())."""
    text = aggregator_registry().render().decode()
    fams = parse_families(text)
    for key in COUNTER_KEYS:
        # The classic text format renders counter families WITH the _total
        # suffix, whatever the declared name was.
        fam_name = f"dynamo_component_worker_{key}"
        if not fam_name.endswith("_total"):
            fam_name += "_total"
        assert fams.get(fam_name, {}).get("type") == "counter", (
            f"{key} must export as a Counter, got {fams.get(fam_name)}"
        )


def test_counter_delta_and_restart_semantics():
    agg = MetricsAggregator(drt=None, namespace="ns", component="backend", endpoint="generate")
    agg.export_stats({1: {"mixed_steps_total": 10}})
    agg.export_stats({1: {"mixed_steps_total": 14}})   # +4
    agg.export_stats({1: {"mixed_steps_total": 3}})    # restart → +3
    text = agg.registry.render().decode()
    line = next(l for l in text.splitlines()
                if l.startswith("dynamo_component_worker_mixed_steps_total{"))
    assert line.endswith(" 17.0"), line


def test_overlap_decode_metrics_render_in_all_roles():
    """The zero-bubble decode pipeline's counters (overlap steps/flushes)
    and host-gap histogram must flow engine → stats → aggregator →
    Prometheus: keys declared in COUNTER_KEYS, emitted by the flight
    recorder / scheduler wire dicts, and rendered as rate()-able counters."""
    from dynamo_tpu.engine.flight_recorder import GAP_BUCKETS, FlightRecorder
    from dynamo_tpu.engine.scheduler import ForwardPassMetrics

    new_keys = (
        "overlap_steps_total", "overlap_flushes_total",
        "decode_host_gap_events_total", "decode_host_gap_seconds_total",
    )
    for key in new_keys:
        assert key in COUNTER_KEYS, f"{key} missing from aggregator COUNTER_KEYS"

    # Flight recorder emits the gap histogram's sum/count counters...
    fr = FlightRecorder()
    fr.record_host_gap(0.003)
    stats = fr.to_stats()
    assert stats["decode_host_gap_events_total"] == 1
    assert stats["decode_host_gap_seconds_total"] > 0
    # ...and the full histogram uses gap-scale buckets (sub-ms floor), not
    # the request-latency defaults.
    buckets, counts = fr.histogram("host_gap")
    assert buckets == GAP_BUCKETS and buckets[0] <= 0.0005
    assert len(counts) == len(buckets) + 1 and sum(counts) == 1
    assert fr.gap_percentile(0.5) <= 0.005 <= fr.gap_percentile(0.99) * 10

    # Scheduler metrics carry the overlap counters on the wire.
    wire = ForwardPassMetrics().to_wire()
    assert "overlap_steps_total" in wire and "overlap_flushes_total" in wire

    # Aggregator renders them as Counter families (rate()-able).
    fams = parse_families(aggregator_registry().render().decode())
    for key in new_keys:
        assert fams.get(f"dynamo_component_worker_{key}", {}).get("type") == "counter", (
            f"{key} not rendered as a counter by the aggregator"
        )


def test_prefix_cache_metrics_render_in_all_roles():
    """Automatic prefix caching's counters must flow engine/mocker stats →
    aggregator → Prometheus: keys declared in COUNTER_KEYS, present on the
    ForwardPassMetrics wire and the mocker's scrape dict, and rendered as
    rate()-able counters."""
    from dynamo_tpu.engine.kv_cache import BlockAllocator
    from dynamo_tpu.engine.scheduler import ForwardPassMetrics
    from dynamo_tpu.llm.mocker import MockTpuEngine
    from dynamo_tpu.llm.tokens import compute_block_hashes

    new_keys = (
        "cached_tokens_total", "prefix_hit_blocks_total",
        "prefix_miss_blocks_total", "prefix_evicted_blocks_total",
        "prefix_onboard_total",
    )
    for key in new_keys:
        assert key in COUNTER_KEYS, f"{key} missing from aggregator COUNTER_KEYS"

    # Wire shape: the scheduler's metrics snapshot carries every key.
    wire = ForwardPassMetrics().to_wire()
    for key in new_keys:
        assert key in wire, f"{key} missing from ForwardPassMetrics wire"

    # Allocator ground truth: hit/miss/evict counters move with the cache.
    alloc = BlockAllocator(4)
    tokens = list(range(32))
    hashes = compute_block_hashes(tokens, 16)
    blocks = alloc.allocate(2)
    alloc.register_hashes(blocks, hashes)
    alloc.release(blocks)
    assert alloc.match_prefix(hashes) == blocks  # hit both
    alloc.release(blocks)
    assert alloc.match_prefix([123456789]) == []  # miss
    assert alloc.hit_blocks_total == 2 and alloc.miss_blocks_total == 1
    alloc.allocate(4)  # forces eviction of the two cached blocks
    assert alloc.evicted_blocks_total == 2

    # Mocker scrape dict exposes the same keys as the real engine's
    # stats_handler (router e2e fleets scrape real hit accounting).
    stats = MockTpuEngine().stats_handler()
    for key in ("cached_tokens_total", "prefix_hit_blocks_total",
                "prefix_miss_blocks_total", "prefix_evicted_blocks_total"):
        assert key in stats, f"{key} missing from mocker stats_handler"

    # Aggregator renders them as Counter families (rate()-able).
    fams = parse_families(aggregator_registry().render().decode())
    for key in new_keys:
        assert fams.get(f"dynamo_component_worker_{key}", {}).get("type") == "counter", (
            f"{key} not rendered as a counter by the aggregator"
        )


def test_tenant_ledger_metrics_render_in_all_roles():
    """Tenant capacity accounting must flow scheduler/mocker →
    stats scrape → aggregator → Prometheus: the flat worker keys are in
    COUNTER_KEYS/GAUGE_KEYS and on the mocker's scrape dict (with the
    nested sketch wire), and the aggregator renders both the worker
    counters and the fleet-merged LABELED per-tenant families."""
    from dynamo_tpu.llm.mocker import MockTpuEngine
    from dynamo_tpu.metrics_aggregator import TENANT_FAMILY_BY_DIM

    flat_counters = (
        "tenant_billed_device_seconds_total", "tenant_billed_kv_block_seconds_total",
        "tenant_billed_queue_seconds_total", "tenant_billed_output_tokens_total",
        "tenant_bills_total", "tenant_slo_attained_total", "tenant_slo_violated_total",
    )
    for key in flat_counters:
        assert key in COUNTER_KEYS, f"{key} missing from aggregator COUNTER_KEYS"
    assert "tenant_tracked" in GAUGE_KEYS

    # Mocker scrape parity: same flat keys + the nested sketch wire the
    # real engine's stats_handler exports.
    stats = MockTpuEngine().stats_handler()
    for key in flat_counters + ("tenant_tracked",):
        assert key in stats, f"{key} missing from mocker stats_handler"
    wire = stats["tenant_ledger"]
    assert set(wire["sketches"]) == {"device_seconds", "kv_block_seconds",
                                     "queue_seconds"}

    # Aggregator: worker counters render rate()-able, and the labeled
    # fleet families carry the tenant label (plus phase for SLO).
    text = aggregator_registry().render().decode()
    fams = parse_families(text)
    for key in flat_counters:
        assert fams.get(f"dynamo_component_worker_{key}", {}).get("type") == "counter", (
            f"{key} not rendered as a counter by the aggregator"
        )
    for fam in set(TENANT_FAMILY_BY_DIM.values()) | {"tenant_slo_attained_total",
                                                     "tenant_slo_violated_total"}:
        assert fams.get(f"dynamo_component_{fam}", {}).get("type") == "counter", (
            f"labeled fleet family {fam} not rendered as a counter"
        )
    assert 'tenant="hygiene"' in text and 'tenant="other"' in text
    # The hygiene bill violates TPOT (200 ms vs a 10 ms target) and attains
    # TTFT — both per-phase labeled samples must render, with the verdict.
    slo_lines = [l for l in text.splitlines()
                 if l.startswith("dynamo_component_tenant_slo_violated_total{")
                 and 'tenant="hygiene"' in l]
    by_phase = {("tpot" if 'phase="tpot"' in l else "ttft"): float(l.rsplit(" ", 1)[1])
                for l in slo_lines}
    assert by_phase == {"ttft": 0.0, "tpot": 1.0}


def test_static_metrics_drift_dtlint_cross_check():
    """The static half of this file's contract, via dtlint MET001: every
    counter emitted on the worker-scrape wire is registered in
    COUNTER_KEYS, every registered key is emitted AND pinned by a Grafana
    panel expr, and the dashboard references no unknown worker keys — so
    this dynamic render test and the MET001 CI gate can never drift apart
    (they read the same key lists and the same dashboard)."""
    import os

    from tools.dtlint import LintConfig, run_lint

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    result = run_lint(
        LintConfig(root=repo),
        rules=["MET001"],
        baseline_path=os.path.join(repo, "dtlint_baseline.json"),
    )
    assert result.findings == [], (
        "metrics drift (code ↔ COUNTER_KEYS/GAUGE_KEYS ↔ Grafana):\n"
        + "\n".join(f.render() for f in result.findings)
    )
    assert result.stale_baseline == [], result.stale_baseline

    # And the cross-check itself is wired to the same registries this
    # file renders: a key list the aggregator doesn't actually export
    # would fail the dynamic tests above.
    for key in COUNTER_KEYS:
        assert key.endswith("_total"), f"counter key {key} must end _total"


def test_get_or_create_rejects_label_mismatch_on_reuse():
    """Regression: sibling registries reusing a collector with a DIFFERENT
    label set must get a clear error at declaration time, not a confusing
    .labels() blow-up (or silent mis-labelling) later."""
    root = MetricsRegistry()
    root.child(worker="a").gauge("shared_metric", "doc").set(1)
    with pytest.raises(ValueError, match="already registered with labels"):
        root.child(zone="b").gauge("shared_metric", "doc")
    # Same label set from another sibling still reuses cleanly.
    root.child(worker="b").gauge("shared_metric", "doc").set(2)
