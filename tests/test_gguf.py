"""GGUF parser tests against a synthetically written file (ref: gguf/ parsing
role — metadata for llama.cpp model cards)."""

import struct

import numpy as np

import pytest

from dynamo_tpu.llm.gguf import GgufError, parse_gguf


def _s(text: str) -> bytes:
    b = text.encode()
    return struct.pack("<Q", len(b)) + b


def write_gguf(path, *, version=3, metadata=(), tensors=()):
    out = b"GGUF" + struct.pack("<IQQ", version, len(tensors), len(metadata))
    for key, vtype, raw in metadata:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, offset in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, offset)
    path.write_bytes(out)


def test_parse_metadata_and_tensors(tmp_path):
    path = tmp_path / "m.gguf"
    tokens_array = struct.pack("<IQ", 8, 2) + _s("<s>") + _s("</s>")  # array of strings
    write_gguf(
        path,
        metadata=[
            ("general.architecture", 8, _s("llama")),
            ("general.name", 8, _s("tiny-test")),
            ("llama.context_length", 4, struct.pack("<I", 4096)),
            ("llama.block_count", 4, struct.pack("<I", 2)),
            ("llama.rope.freq_base", 6, struct.pack("<f", 10000.0)),
            ("tokenizer.ggml.model", 8, _s("gpt2")),
            ("tokenizer.ggml.tokens", 9, tokens_array),
            ("general.quantized", 7, b"\x01"),
        ],
        tensors=[
            ("token_embd.weight", [256, 64], 0, 0),
            ("blk.0.attn_q.weight", [64, 64], 30, 65536),
        ],
    )
    meta = parse_gguf(str(path))
    assert meta.version == 3
    assert meta.architecture == "llama"
    assert meta.model_name == "tiny-test"
    assert meta.context_length == 4096
    assert meta.num_layers == 2
    assert meta.tokenizer_model == "gpt2"
    assert meta.tokens == ["<s>", "</s>"]
    assert meta.metadata["general.quantized"] is True
    assert abs(meta.metadata["llama.rope.freq_base"] - 10000.0) < 1e-3
    assert len(meta.tensors) == 2
    t = meta.tensors[1]
    assert t.name == "blk.0.attn_q.weight" and t.shape == [64, 64]
    assert t.dtype_name == "bf16" and t.offset == 65536


def test_rejects_bad_magic(tmp_path):
    p = tmp_path / "bad.gguf"
    p.write_bytes(b"NOPE" + b"\x00" * 100)
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_truncated(tmp_path):
    p = tmp_path / "trunc.gguf"
    write_gguf(p, metadata=[("general.architecture", 8, _s("llama"))])
    data = p.read_bytes()
    p.write_bytes(data[:-4])
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_unknown_version(tmp_path):
    p = tmp_path / "v9.gguf"
    write_gguf(p, version=9)
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def write_gguf_with_data(path, metadata, named_arrays):
    """Write a full GGUF file: header + directory + aligned f32 tensor data.
    ``named_arrays``: [(name, np.ndarray f32 in logical [out, in] shape)] —
    stored with ggml's reversed ne convention."""
    import numpy as np

    align = 32
    tensors = []
    blobs = []
    offset = 0
    for name, arr in named_arrays:
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        dims = list(reversed(arr.shape))  # ne[0] = contiguous dim
        tensors.append((name, dims, 0, offset))
        raw = arr.tobytes()
        pad = (-len(raw)) % align
        blobs.append(raw + b"\0" * pad)
        offset += len(raw) + pad
    out = b"GGUF" + struct.pack("<IQQ", 3, len(tensors), len(metadata))
    for key, vtype, raw in metadata:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, off in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, off)
    out += b"\0" * ((-len(out)) % align)
    for b in blobs:
        out += b
    path.write_bytes(out)


def test_load_gguf_checkpoint_roundtrip(tmp_path):
    """A tiny model's params exported to GGUF load back identically (f32),
    and config_from_gguf reconstructs the architecture (ref: local_model.rs
    GGUF resolution + the engines' gguf loading)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.weights import config_from_gguf, load_gguf_checkpoint

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = params["layers"]

    arrays = [
        ("token_embd.weight", np.asarray(params["embed"])),
        ("output_norm.weight", np.asarray(params["final_norm"])),
        ("output.weight", np.asarray(params["lm_head"]).T),  # HF [out, in]
    ]
    for l in range(cfg.num_layers):
        arrays += [
            (f"blk.{l}.attn_norm.weight", np.asarray(lp["attn_norm"][l])),
            (f"blk.{l}.ffn_norm.weight", np.asarray(lp["mlp_norm"][l])),
            (f"blk.{l}.attn_q.weight", np.asarray(lp["wq"][l]).T),
            (f"blk.{l}.attn_k.weight", np.asarray(lp["wk"][l]).T),
            (f"blk.{l}.attn_v.weight", np.asarray(lp["wv"][l]).T),
            (f"blk.{l}.attn_output.weight", np.asarray(lp["wo"][l]).T),
            (f"blk.{l}.ffn_gate.weight", np.asarray(lp["w_gate"][l]).T),
            (f"blk.{l}.ffn_up.weight", np.asarray(lp["w_up"][l]).T),
            (f"blk.{l}.ffn_down.weight", np.asarray(lp["w_down"][l]).T),
        ]
    meta = [
        ("general.architecture", 8, _s("llama")),
        ("general.name", 8, _s("tiny-gguf")),
        ("llama.context_length", 4, struct.pack("<I", cfg.max_seq_len)),
        ("llama.block_count", 4, struct.pack("<I", cfg.num_layers)),
        ("llama.embedding_length", 4, struct.pack("<I", cfg.hidden_size)),
        ("llama.feed_forward_length", 4, struct.pack("<I", cfg.intermediate_size)),
        ("llama.attention.head_count", 4, struct.pack("<I", cfg.num_heads)),
        ("llama.attention.head_count_kv", 4, struct.pack("<I", cfg.num_kv_heads)),
        ("llama.rope.freq_base", 6, struct.pack("<f", cfg.rope_theta)),
        ("llama.attention.layer_norm_rms_epsilon", 6, struct.pack("<f", cfg.rms_norm_eps)),
    ]
    path = tmp_path / "tiny.gguf"
    write_gguf_with_data(path, meta, arrays)

    got_cfg = config_from_gguf(str(path))
    assert got_cfg.hidden_size == cfg.hidden_size
    assert got_cfg.num_layers == cfg.num_layers
    assert got_cfg.num_kv_heads == cfg.num_kv_heads
    assert got_cfg.vocab_size == cfg.vocab_size
    assert not got_cfg.tie_word_embeddings  # output.weight present

    loaded = load_gguf_checkpoint(str(path), cfg, dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_q8_0_dequant(tmp_path):
    """q8_0 blocks (f16 scale + 32 int8) dequantize to scale*code."""
    import numpy as np

    from dynamo_tpu.llm.gguf import load_tensors

    codes = np.arange(-16, 16, dtype=np.int8)
    scale = np.float16(0.5)
    raw = scale.tobytes() + codes.tobytes()
    align = 32
    out = b"GGUF" + struct.pack("<IQQ", 3, 1, 0)
    out += _s("t") + struct.pack("<I", 1) + struct.pack("<Q", 32) + struct.pack("<IQ", 8, 0)
    out += b"\0" * ((-len(out)) % align)
    out += raw
    path = tmp_path / "q.gguf"
    path.write_bytes(out)
    t = load_tensors(str(path))["t"]
    np.testing.assert_allclose(t, codes.astype(np.float32) * 0.5)


def test_resolve_hf_cache_layout(tmp_path, monkeypatch):
    """resolve_model follows the HF hub cache layout with refs/main
    (ref: hub.rs:299 resolution)."""
    from dynamo_tpu.engine.weights import resolve_model

    repo = tmp_path / "hub" / "models--org--model"
    snap = repo / "snapshots" / "abc123"
    snap.mkdir(parents=True)
    (snap / "model.safetensors").write_bytes(b"x")
    (repo / "refs").mkdir()
    (repo / "refs" / "main").write_text("abc123\n")
    monkeypatch.setenv("HF_HOME", str(tmp_path))
    assert resolve_model("org/model") == str(snap)
    assert resolve_model("org/missing") is None
    # Direct GGUF file path resolves to itself.
    g = tmp_path / "m.gguf"
    g.write_bytes(b"GGUF")
    assert resolve_model(str(g)) == str(g)


# --- k-quants (q4_k / q5_k / q6_k) -----------------------------------------
# Encoders below re-derive llama.cpp's block layouts independently (simple
# max-based scale selection) so the repo's dequantizers are checked against
# a second implementation of the spec, not against themselves.


def _pack_scales_k4(sc, mn):
    """Inverse of gguf._scale_min_k4: 8 six-bit (scale, min) pairs → 12 bytes."""
    out = np.zeros(12, np.uint8)
    for j in range(4):
        out[j] = (sc[j] & 63) | ((sc[j + 4] >> 4) << 6)
        out[j + 4] = (mn[j] & 63) | ((mn[j + 4] >> 4) << 6)
        out[j + 8] = (sc[j + 4] & 0xF) | ((mn[j + 4] & 0xF) << 4)
    return out


def _encode_q4_k(x):
    """x [n, 256] f32 → q4_k blocks [n, 144] uint8 (non-negative values,
    dmin=0, per-sub-block max scaling)."""
    n = x.shape[0]
    out = np.zeros((n, 144), np.uint8)
    for i in range(n):
        sub = x[i].reshape(8, 32)
        smax = np.max(sub, axis=1)
        d = float(np.max(smax) / (63 * 15)) or 1.0
        sc = np.clip(np.round(smax / (d * 15)), 1, 63).astype(np.uint8)
        q = np.clip(np.round(sub / (d * sc[:, None])), 0, 15).astype(np.uint8)
        out[i, 0:2] = np.frombuffer(np.float16(d).tobytes(), np.uint8)
        out[i, 2:4] = np.frombuffer(np.float16(0.0).tobytes(), np.uint8)
        out[i, 4:16] = _pack_scales_k4(sc, np.zeros(8, np.uint8))
        qs = np.zeros(128, np.uint8)
        for j in range(4):  # chunk j holds sub-blocks 2j (low) and 2j+1 (high)
            qs[32 * j : 32 * (j + 1)] = q[2 * j] | (q[2 * j + 1] << 4)
        out[i, 16:144] = qs
    return out


def test_q4_k_dequant_matches_independent_encoder(tmp_path):
    from dynamo_tpu.llm.gguf import _dequant_q4_k

    rng = np.random.default_rng(3)
    x = np.abs(rng.standard_normal((4, 256), dtype=np.float32))
    blocks = _encode_q4_k(x)
    back = _dequant_q4_k(blocks.tobytes()).reshape(4, 256)
    # error bounded by one quantization step of each sub-block grid
    sub = x.reshape(4, 8, 32)
    step = np.max(sub, axis=2, keepdims=True) / 15 + 1e-6
    assert np.all(np.abs(back.reshape(4, 8, 32) - sub) <= step * 1.01)


def _encode_q6_k(x):
    """x [n, 256] f32 → q6_k blocks [n, 210] uint8 (per-16-lane int8 scales)."""
    n = x.shape[0]
    out = np.zeros((n, 210), np.uint8)
    for i in range(n):
        d = float(np.max(np.abs(x[i])) / (31 * 32)) or 1.0
        groups = x[i].reshape(16, 16)
        sc = np.clip(np.round(np.max(np.abs(groups), axis=1) / (d * 31)), 1, 127).astype(np.int8)
        q = np.clip(np.round(x[i] / (d * np.repeat(sc.astype(np.float32), 16))), -32, 31).astype(np.int16) + 32
        ql = np.zeros(128, np.uint8)
        qh = np.zeros(64, np.uint8)
        for half in range(2):
            qq = q[128 * half : 128 * (half + 1)]
            q1, q2, q3, q4 = qq[0:32], qq[32:64], qq[64:96], qq[96:128]
            ql[64 * half : 64 * half + 32] = (q1 & 0xF) | ((q3 & 0xF) << 4)
            ql[64 * half + 32 : 64 * half + 64] = (q2 & 0xF) | ((q4 & 0xF) << 4)
            qh[32 * half : 32 * half + 32] = (
                (q1 >> 4) | ((q2 >> 4) << 2) | ((q3 >> 4) << 4) | ((q4 >> 4) << 6)
            )
        out[i, 0:128] = ql
        out[i, 128:192] = qh
        out[i, 192:208] = sc.view(np.uint8)
        out[i, 208:210] = np.frombuffer(np.float16(d).tobytes(), np.uint8)
    return out


def test_q6_k_dequant_matches_independent_encoder():
    from dynamo_tpu.llm.gguf import _dequant_q6_k

    rng = np.random.default_rng(5)
    x = rng.standard_normal((3, 256), dtype=np.float32)
    blocks = _encode_q6_k(x)
    back = _dequant_q6_k(blocks.tobytes()).reshape(3, 256)
    groups = x.reshape(3, 16, 16)
    step = np.max(np.abs(groups), axis=2, keepdims=True) / 31 + 1e-6
    assert np.all(np.abs(back.reshape(3, 16, 16) - groups) <= step * 1.05)


def test_q5_k_dequant_five_bit_range():
    """q5_k layout check with hand-built blocks: nibble + high-bit lanes land
    in the right elements (d=1, sc=1, dmin=0 → output == 5-bit code)."""
    from dynamo_tpu.llm.gguf import _dequant_q5_k

    block = np.zeros(176, np.uint8)
    block[0:2] = np.frombuffer(np.float16(1.0).tobytes(), np.uint8)  # d=1
    block[2:4] = np.frombuffer(np.float16(0.0).tobytes(), np.uint8)  # dmin=0
    block[4:16] = _pack_scales_k4(np.ones(8, np.uint8), np.zeros(8, np.uint8))
    codes = (np.arange(256) % 32).astype(np.uint8)  # every 5-bit value
    qs = np.zeros(128, np.uint8)
    qh = np.zeros(32, np.uint8)
    for j in range(4):
        c1 = codes[64 * j : 64 * j + 32]
        c2 = codes[64 * j + 32 : 64 * j + 64]
        qs[32 * j : 32 * (j + 1)] = (c1 & 0xF) | ((c2 & 0xF) << 4)
        qh |= ((c1 >> 4) << (2 * j)) | ((c2 >> 4) << (2 * j + 1))
    block[16:48] = qh
    block[48:176] = qs
    back = _dequant_q5_k(block.tobytes())
    np.testing.assert_array_equal(back, codes.astype(np.float32))


def test_q4_k_checkpoint_generates(tmp_path):
    """A q4_k GGUF checkpoint loads and generates end-to-end (VERDICT r4
    Missing #4: most published GGUF checkpoints are k-quants)."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.kv_cache import KvCacheArrays
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.weights import load_gguf_checkpoint

    cfg = get_config("tiny")
    dense = llama.init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)

    # Build the GGUF with q4_k matrices (padded shapes: tiny dims aren't
    # multiples of 256, so use f32 for small tensors and q4_k where the
    # element count allows).
    align = 32
    tensors, blobs, offset = [], [], 0

    def add(name, arr, as_q4k):
        nonlocal offset
        a = np.ascontiguousarray(np.asarray(arr, np.float32))
        dims = list(reversed(a.shape))
        if as_q4k and a.size % 256 == 0:
            flat = np.abs(a.reshape(-1, 256))  # encoder handles non-negative
            raw = _encode_q4_k(flat).tobytes()
            gtype = 12
        else:
            raw = a.tobytes()
            gtype = 0
        pad = (-len(raw)) % align
        tensors.append((name, dims, gtype, offset))
        blobs.append(raw + b"\0" * pad)
        offset += len(raw) + pad

    def hf(t):  # [in, out] stacked → per-layer HF [out, in]
        return np.asarray(t, np.float32)

    add("token_embd.weight", hf(dense["embed"]), True)
    add("output_norm.weight", hf(dense["final_norm"]), False)
    names = {"wq": "attn_q", "wk": "attn_k", "wv": "attn_v", "wo": "attn_output",
             "w_gate": "ffn_gate", "w_up": "ffn_up", "w_down": "ffn_down"}
    for l in range(cfg.num_layers):
        add(f"blk.{l}.attn_norm.weight", hf(dense["layers"]["attn_norm"][l]), False)
        add(f"blk.{l}.ffn_norm.weight", hf(dense["layers"]["mlp_norm"][l]), False)
        for k, gname in names.items():
            add(f"blk.{l}.{gname}.weight", hf(dense["layers"][k][l]).T, True)

    meta = [
        ("general.architecture", 8, _s("llama")),
        ("llama.embedding_length", 4, struct.pack("<I", cfg.hidden_size)),
        ("llama.block_count", 4, struct.pack("<I", cfg.num_layers)),
        ("llama.attention.head_count", 4, struct.pack("<I", cfg.num_heads)),
        ("llama.attention.head_count_kv", 4, struct.pack("<I", cfg.num_kv_heads)),
        ("llama.attention.key_length", 4, struct.pack("<I", cfg.head_dim)),
        ("llama.feed_forward_length", 4, struct.pack("<I", cfg.intermediate_size)),
        ("llama.context_length", 4, struct.pack("<I", cfg.max_seq_len)),
    ]
    out = b"GGUF" + struct.pack("<IQQ", 3, len(tensors), len(meta))
    for key, vtype, raw in meta:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, off in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for dd in dims:
            out += struct.pack("<Q", dd)
        out += struct.pack("<IQ", gtype, off)
    pad = (-len(out)) % align
    out += b"\0" * pad + b"".join(blobs)
    p = tmp_path / "kq.gguf"
    p.write_bytes(out)

    params = load_gguf_checkpoint(str(p), cfg, dtype=jnp.float32)
    cache = KvCacheArrays.create(cfg, 16, dtype=jnp.float32)
    tables = jnp.tile(jnp.arange(1, 5, dtype=jnp.int32), (2, 1))
    toks = jnp.array([3, 7], jnp.int32)
    pos = jnp.array([10, 4], jnp.int32)
    act = jnp.ones((2,), bool)
    logits, _, _ = llama.decode(params, cfg, cache.k, cache.v, toks, pos, tables, act)
    assert np.isfinite(np.asarray(logits)).all()


def test_q4_k_min_offsets_decode():
    """The packed 6-bit MIN lanes must decode too: qs=0 → out = -dmin*m[j]."""
    from dynamo_tpu.llm.gguf import _dequant_q4_k

    block = np.zeros(144, np.uint8)
    block[0:2] = np.frombuffer(np.float16(1.0).tobytes(), np.uint8)
    block[2:4] = np.frombuffer(np.float16(2.0).tobytes(), np.uint8)  # dmin=2
    mins = np.array([1, 5, 17, 33, 47, 20, 63, 9], np.uint8)  # spans both packings
    block[4:16] = _pack_scales_k4(np.ones(8, np.uint8), mins)
    back = _dequant_q4_k(block.tobytes()).reshape(8, 32)
    np.testing.assert_allclose(back, np.broadcast_to(-2.0 * mins[:, None].astype(np.float32), (8, 32)))
