"""GGUF parser tests against a synthetically written file (ref: gguf/ parsing
role — metadata for llama.cpp model cards)."""

import struct

import pytest

from dynamo_tpu.llm.gguf import GgufError, parse_gguf


def _s(text: str) -> bytes:
    b = text.encode()
    return struct.pack("<Q", len(b)) + b


def write_gguf(path, *, version=3, metadata=(), tensors=()):
    out = b"GGUF" + struct.pack("<IQQ", version, len(tensors), len(metadata))
    for key, vtype, raw in metadata:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, offset in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, offset)
    path.write_bytes(out)


def test_parse_metadata_and_tensors(tmp_path):
    path = tmp_path / "m.gguf"
    tokens_array = struct.pack("<IQ", 8, 2) + _s("<s>") + _s("</s>")  # array of strings
    write_gguf(
        path,
        metadata=[
            ("general.architecture", 8, _s("llama")),
            ("general.name", 8, _s("tiny-test")),
            ("llama.context_length", 4, struct.pack("<I", 4096)),
            ("llama.block_count", 4, struct.pack("<I", 2)),
            ("llama.rope.freq_base", 6, struct.pack("<f", 10000.0)),
            ("tokenizer.ggml.model", 8, _s("gpt2")),
            ("tokenizer.ggml.tokens", 9, tokens_array),
            ("general.quantized", 7, b"\x01"),
        ],
        tensors=[
            ("token_embd.weight", [256, 64], 0, 0),
            ("blk.0.attn_q.weight", [64, 64], 30, 65536),
        ],
    )
    meta = parse_gguf(str(path))
    assert meta.version == 3
    assert meta.architecture == "llama"
    assert meta.model_name == "tiny-test"
    assert meta.context_length == 4096
    assert meta.num_layers == 2
    assert meta.tokenizer_model == "gpt2"
    assert meta.tokens == ["<s>", "</s>"]
    assert meta.metadata["general.quantized"] is True
    assert abs(meta.metadata["llama.rope.freq_base"] - 10000.0) < 1e-3
    assert len(meta.tensors) == 2
    t = meta.tensors[1]
    assert t.name == "blk.0.attn_q.weight" and t.shape == [64, 64]
    assert t.dtype_name == "bf16" and t.offset == 65536


def test_rejects_bad_magic(tmp_path):
    p = tmp_path / "bad.gguf"
    p.write_bytes(b"NOPE" + b"\x00" * 100)
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_truncated(tmp_path):
    p = tmp_path / "trunc.gguf"
    write_gguf(p, metadata=[("general.architecture", 8, _s("llama"))])
    data = p.read_bytes()
    p.write_bytes(data[:-4])
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_unknown_version(tmp_path):
    p = tmp_path / "v9.gguf"
    write_gguf(p, version=9)
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def write_gguf_with_data(path, metadata, named_arrays):
    """Write a full GGUF file: header + directory + aligned f32 tensor data.
    ``named_arrays``: [(name, np.ndarray f32 in logical [out, in] shape)] —
    stored with ggml's reversed ne convention."""
    import numpy as np

    align = 32
    tensors = []
    blobs = []
    offset = 0
    for name, arr in named_arrays:
        arr = np.ascontiguousarray(arr, dtype=np.float32)
        dims = list(reversed(arr.shape))  # ne[0] = contiguous dim
        tensors.append((name, dims, 0, offset))
        raw = arr.tobytes()
        pad = (-len(raw)) % align
        blobs.append(raw + b"\0" * pad)
        offset += len(raw) + pad
    out = b"GGUF" + struct.pack("<IQQ", 3, len(tensors), len(metadata))
    for key, vtype, raw in metadata:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, off in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, off)
    out += b"\0" * ((-len(out)) % align)
    for b in blobs:
        out += b
    path.write_bytes(out)


def test_load_gguf_checkpoint_roundtrip(tmp_path):
    """A tiny model's params exported to GGUF load back identically (f32),
    and config_from_gguf reconstructs the architecture (ref: local_model.rs
    GGUF resolution + the engines' gguf loading)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.weights import config_from_gguf, load_gguf_checkpoint

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = params["layers"]

    arrays = [
        ("token_embd.weight", np.asarray(params["embed"])),
        ("output_norm.weight", np.asarray(params["final_norm"])),
        ("output.weight", np.asarray(params["lm_head"]).T),  # HF [out, in]
    ]
    for l in range(cfg.num_layers):
        arrays += [
            (f"blk.{l}.attn_norm.weight", np.asarray(lp["attn_norm"][l])),
            (f"blk.{l}.ffn_norm.weight", np.asarray(lp["mlp_norm"][l])),
            (f"blk.{l}.attn_q.weight", np.asarray(lp["wq"][l]).T),
            (f"blk.{l}.attn_k.weight", np.asarray(lp["wk"][l]).T),
            (f"blk.{l}.attn_v.weight", np.asarray(lp["wv"][l]).T),
            (f"blk.{l}.attn_output.weight", np.asarray(lp["wo"][l]).T),
            (f"blk.{l}.ffn_gate.weight", np.asarray(lp["w_gate"][l]).T),
            (f"blk.{l}.ffn_up.weight", np.asarray(lp["w_up"][l]).T),
            (f"blk.{l}.ffn_down.weight", np.asarray(lp["w_down"][l]).T),
        ]
    meta = [
        ("general.architecture", 8, _s("llama")),
        ("general.name", 8, _s("tiny-gguf")),
        ("llama.context_length", 4, struct.pack("<I", cfg.max_seq_len)),
        ("llama.block_count", 4, struct.pack("<I", cfg.num_layers)),
        ("llama.embedding_length", 4, struct.pack("<I", cfg.hidden_size)),
        ("llama.feed_forward_length", 4, struct.pack("<I", cfg.intermediate_size)),
        ("llama.attention.head_count", 4, struct.pack("<I", cfg.num_heads)),
        ("llama.attention.head_count_kv", 4, struct.pack("<I", cfg.num_kv_heads)),
        ("llama.rope.freq_base", 6, struct.pack("<f", cfg.rope_theta)),
        ("llama.attention.layer_norm_rms_epsilon", 6, struct.pack("<f", cfg.rms_norm_eps)),
    ]
    path = tmp_path / "tiny.gguf"
    write_gguf_with_data(path, meta, arrays)

    got_cfg = config_from_gguf(str(path))
    assert got_cfg.hidden_size == cfg.hidden_size
    assert got_cfg.num_layers == cfg.num_layers
    assert got_cfg.num_kv_heads == cfg.num_kv_heads
    assert got_cfg.vocab_size == cfg.vocab_size
    assert not got_cfg.tie_word_embeddings  # output.weight present

    loaded = load_gguf_checkpoint(str(path), cfg, dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def test_q8_0_dequant(tmp_path):
    """q8_0 blocks (f16 scale + 32 int8) dequantize to scale*code."""
    import numpy as np

    from dynamo_tpu.llm.gguf import load_tensors

    codes = np.arange(-16, 16, dtype=np.int8)
    scale = np.float16(0.5)
    raw = scale.tobytes() + codes.tobytes()
    align = 32
    out = b"GGUF" + struct.pack("<IQQ", 3, 1, 0)
    out += _s("t") + struct.pack("<I", 1) + struct.pack("<Q", 32) + struct.pack("<IQ", 8, 0)
    out += b"\0" * ((-len(out)) % align)
    out += raw
    path = tmp_path / "q.gguf"
    path.write_bytes(out)
    t = load_tensors(str(path))["t"]
    np.testing.assert_allclose(t, codes.astype(np.float32) * 0.5)


def test_resolve_hf_cache_layout(tmp_path, monkeypatch):
    """resolve_model follows the HF hub cache layout with refs/main
    (ref: hub.rs:299 resolution)."""
    from dynamo_tpu.engine.weights import resolve_model

    repo = tmp_path / "hub" / "models--org--model"
    snap = repo / "snapshots" / "abc123"
    snap.mkdir(parents=True)
    (snap / "model.safetensors").write_bytes(b"x")
    (repo / "refs").mkdir()
    (repo / "refs" / "main").write_text("abc123\n")
    monkeypatch.setenv("HF_HOME", str(tmp_path))
    assert resolve_model("org/model") == str(snap)
    assert resolve_model("org/missing") is None
    # Direct GGUF file path resolves to itself.
    g = tmp_path / "m.gguf"
    g.write_bytes(b"GGUF")
    assert resolve_model(str(g)) == str(g)
