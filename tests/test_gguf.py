"""GGUF parser tests against a synthetically written file (ref: gguf/ parsing
role — metadata for llama.cpp model cards)."""

import struct

import pytest

from dynamo_tpu.llm.gguf import GgufError, parse_gguf


def _s(text: str) -> bytes:
    b = text.encode()
    return struct.pack("<Q", len(b)) + b


def write_gguf(path, *, version=3, metadata=(), tensors=()):
    out = b"GGUF" + struct.pack("<IQQ", version, len(tensors), len(metadata))
    for key, vtype, raw in metadata:
        out += _s(key) + struct.pack("<I", vtype) + raw
    for name, dims, gtype, offset in tensors:
        out += _s(name) + struct.pack("<I", len(dims))
        for d in dims:
            out += struct.pack("<Q", d)
        out += struct.pack("<IQ", gtype, offset)
    path.write_bytes(out)


def test_parse_metadata_and_tensors(tmp_path):
    path = tmp_path / "m.gguf"
    tokens_array = struct.pack("<IQ", 8, 2) + _s("<s>") + _s("</s>")  # array of strings
    write_gguf(
        path,
        metadata=[
            ("general.architecture", 8, _s("llama")),
            ("general.name", 8, _s("tiny-test")),
            ("llama.context_length", 4, struct.pack("<I", 4096)),
            ("llama.block_count", 4, struct.pack("<I", 2)),
            ("llama.rope.freq_base", 6, struct.pack("<f", 10000.0)),
            ("tokenizer.ggml.model", 8, _s("gpt2")),
            ("tokenizer.ggml.tokens", 9, tokens_array),
            ("general.quantized", 7, b"\x01"),
        ],
        tensors=[
            ("token_embd.weight", [256, 64], 0, 0),
            ("blk.0.attn_q.weight", [64, 64], 30, 65536),
        ],
    )
    meta = parse_gguf(str(path))
    assert meta.version == 3
    assert meta.architecture == "llama"
    assert meta.model_name == "tiny-test"
    assert meta.context_length == 4096
    assert meta.num_layers == 2
    assert meta.tokenizer_model == "gpt2"
    assert meta.tokens == ["<s>", "</s>"]
    assert meta.metadata["general.quantized"] is True
    assert abs(meta.metadata["llama.rope.freq_base"] - 10000.0) < 1e-3
    assert len(meta.tensors) == 2
    t = meta.tensors[1]
    assert t.name == "blk.0.attn_q.weight" and t.shape == [64, 64]
    assert t.dtype_name == "bf16" and t.offset == 65536


def test_rejects_bad_magic(tmp_path):
    p = tmp_path / "bad.gguf"
    p.write_bytes(b"NOPE" + b"\x00" * 100)
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_truncated(tmp_path):
    p = tmp_path / "trunc.gguf"
    write_gguf(p, metadata=[("general.architecture", 8, _s("llama"))])
    data = p.read_bytes()
    p.write_bytes(data[:-4])
    with pytest.raises(GgufError):
        parse_gguf(str(p))


def test_rejects_unknown_version(tmp_path):
    p = tmp_path / "v9.gguf"
    write_gguf(p, version=9)
    with pytest.raises(GgufError):
        parse_gguf(str(p))
