"""Incident autopsy plane: anomaly detection, black-box capture, tail
sampling, on-demand profiling, and slow-path attribution.

The determinism tests drive the detector with a monkeypatched clock and
synthetic digest streams and pin EXACT (reason, fire-count) sequences —
the property that makes incident counts trustworthy. The e2e test injects
a synthetic queue-wait spike through the demo stack (frontend → router →
worker → scheduler) and asserts exactly ONE debounced bundle whose
``tools/autopsy.py`` report attributes the spike to queue wait.
"""

import asyncio
import glob
import json
import os
import subprocess
import sys

import aiohttp
import pytest

from dynamo_tpu.runtime.incidents import (
    BUNDLE_SCHEMA,
    AnomalyDetector,
    DetectorConfig,
    IncidentConfig,
    IncidentPlane,
    IncidentRecorder,
    REASONS,
)
from dynamo_tpu.runtime.telemetry import LatencyDigest
from dynamo_tpu.runtime.tracing import configure_tracing, get_tracer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import autopsy  # noqa: E402  (tools/autopsy.py)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def digest_wire(values):
    """A {"window", "total"} digest wire payload over explicit samples —
    the synthetic stream the detector consumes."""
    d = LatencyDigest()
    for v in values:
        d.observe(v)
    w = d.to_wire()
    return {"window": w, "total": w}


def stats_with(**streams):
    return {"digests": {name: digest_wire(vals) for name, vals in streams.items()}}


# --- detector determinism ----------------------------------------------------

def test_detector_exact_fire_sequence():
    """Monkeypatched clock + synthetic digest stream → exact (reason,
    fire-count) sequence: baseline warmup, spike fire, debounce hold,
    re-fire past debounce, recovery."""
    clock = FakeClock()
    det = AnomalyDetector(
        DetectorConfig(min_window_count=4, baseline_checks=2, debounce_s=10.0,
                       min_abs_s=0.005, jump_factor=3.0),
        clock=clock,
    )
    calm, spike = [0.01] * 8, [0.2] * 8
    script = [
        (1.0, calm, []),            # check 1: baseline set
        (2.0, calm, []),            # check 2
        (3.0, calm, []),            # check 3: armed, still calm
        (4.0, spike, ["ttft_p99"]),  # 20x jump fires
        (5.0, spike, []),            # debounced (1s < 10s)
        (13.5, spike, []),           # still debounced (9.5s < 10s)
        (14.5, spike, ["ttft_p99"]),  # past debounce: re-fires
        (15.0, calm, []),            # recovered (baseline was frozen)
    ]
    for t, vals, expect in script:
        clock.t = t
        assert det.update(stats_with(ttft=vals)) == expect, f"at t={t}"
    assert det.fired_total == 2
    assert det.checks_total == len(script)
    snap = det.snapshot()
    assert snap["baselines"]["ttft_p99"] == pytest.approx(0.01, rel=0.05)


def test_detector_below_min_count_never_judges():
    clock = FakeClock()
    det = AnomalyDetector(
        DetectorConfig(min_window_count=8, baseline_checks=1), clock=clock
    )
    for i in range(5):
        clock.t = float(i)
        assert det.update(stats_with(queue_wait=[5.0] * 4)) == []  # 4 < 8 samples
    assert det.fired_total == 0


def test_detector_discrete_signals():
    """Compile increments, stall transitions, and SLO violation-rate steps
    each fire exactly on their edge."""
    clock = FakeClock(1.0)
    det = AnomalyDetector(DetectorConfig(debounce_s=5.0, min_judged=4), clock=clock)

    # post_warmup_compile: first sight is baseline, increments fire.
    assert det.update({"compiles_after_warmup_total": 0}) == []
    clock.t = 2.0
    assert det.update({"compiles_after_warmup_total": 1}) == ["post_warmup_compile"]
    clock.t = 3.0
    assert det.update({"compiles_after_warmup_total": 1}) == []  # no new compile
    clock.t = 4.0
    assert det.update({"compiles_after_warmup_total": 2}) == []  # debounced
    clock.t = 8.0
    assert det.update({"compiles_after_warmup_total": 3}) == ["post_warmup_compile"]

    # engine_stall: only the 0 → 1 transition fires.
    clock.t = 20.0
    assert det.update({"engine_stalled": 1.0}) == ["engine_stall"]
    clock.t = 21.0
    assert det.update({"engine_stalled": 1.0}) == []
    clock.t = 22.0
    assert det.update({"engine_stalled": 0.0}) == []
    clock.t = 30.0
    assert det.update({"engine_stalled": 1.0}) == ["engine_stall"]

    # slo_violation: rate over the scrape delta, min_judged gated.
    clock.t = 40.0
    assert det.update({"slo_ttft_attained_total": 10, "slo_ttft_violated_total": 0}) == []
    clock.t = 41.0
    # +2 judged < min_judged: not evaluated.
    assert det.update({"slo_ttft_attained_total": 10, "slo_ttft_violated_total": 2}) == []
    clock.t = 42.0
    # +4 judged, 3 violated → rate 0.75 ≥ 0.5.
    assert det.update({"slo_ttft_attained_total": 11, "slo_ttft_violated_total": 5}) == [
        "slo_violation"
    ]


def test_detector_host_gap_regression():
    clock = FakeClock()
    det = AnomalyDetector(
        DetectorConfig(baseline_checks=2, min_gap_events=10, gap_factor=3.0,
                       min_gap_abs_s=0.0005, debounce_s=5.0),
        clock=clock,
    )

    def gap_stats(events, seconds):
        return {"decode_host_gap_events_total": events,
                "decode_host_gap_seconds_total": seconds}

    clock.t = 1.0
    assert det.update(gap_stats(0, 0.0)) == []  # first sight
    # Three calm scrapes: mean gap 0.5 ms each, builds + arms the baseline.
    fires = []
    for i, (ev, s) in enumerate([(20, 0.01), (40, 0.02), (60, 0.03)]):
        clock.t = 2.0 + i
        fires += det.update(gap_stats(ev, s))
    assert fires == []
    # Regression: mean gap 5 ms over the next delta (10x).
    clock.t = 10.0
    assert det.update(gap_stats(80, 0.13)) == ["host_gap"]


# --- recorder: rate limit + LRU retention ------------------------------------

def test_recorder_rate_limit_and_lru(tmp_path):
    clock = FakeClock(100.0)
    rec = IncidentRecorder(dir=str(tmp_path), keep=2, min_interval_s=30.0, clock=clock)

    p1 = rec.capture("ttft_p99", {"value": 1}, {"stats": {}})
    assert p1 is not None and os.path.exists(p1)
    # Within the rate-limit floor: counted as suppressed, no bundle.
    clock.t = 110.0
    assert rec.capture("queue_wait_p99", {"value": 2}, {"stats": {}}) is None
    assert rec.rate_limited_total == 1
    # Edge: exactly at the floor is still limited; past it captures.
    clock.t = 129.999
    assert rec.capture("queue_wait_p99", {"value": 2}, {"stats": {}}) is None
    clock.t = 130.1
    p2 = rec.capture("queue_wait_p99", {"value": 2}, {"stats": {}})
    assert p2 is not None
    # Third capture evicts the oldest bundle file (keep=2).
    clock.t = 170.0
    p3 = rec.capture("engine_stall", {"value": 3}, {"stats": {}})
    assert p3 is not None
    assert not os.path.exists(p1), "LRU retention should drop the oldest bundle"
    assert os.path.exists(p2) and os.path.exists(p3)

    stats = rec.to_stats()
    assert stats["incidents_total"] == 3
    assert stats["incidents_ttft_p99_total"] == 1
    assert stats["incidents_queue_wait_p99_total"] == 1
    assert stats["incidents_engine_stall_total"] == 1
    assert stats["incident_last_age_s"] == 0.0
    assert len(rec.list()) == 2


def test_recorder_counts_without_dir():
    clock = FakeClock()
    rec = IncidentRecorder(dir=None, keep=4, min_interval_s=0.0, clock=clock)
    assert rec.capture("host_gap", {}, {}) is None
    assert rec.to_stats()["incidents_total"] == 1
    assert rec.last_capture["status"] == "counted"


# --- bundle round-trip through the autopsy -----------------------------------

def test_bundle_roundtrip_autopsy(tmp_path):
    """plane.observe(synthetic spike) → bundle on disk → autopsy parses it
    and attributes the incident to the injected phase; the embedded trace
    ring round-trips into a per-request report."""
    configure_tracing(path=None, sample=1.0, ring_size=64, service="test")
    try:
        tracer = get_tracer()
        tid = "ef" * 16
        # A request's lifecycle events land in the ring (ring-only mode —
        # no trace file anywhere).
        tracer.event("queued", tid, service="scheduler", prompt_tokens=12)
        tracer.event("admitted", tid, service="scheduler", queue_s=0.45)
        tracer.event("first_token", tid, service="scheduler", ttft_s=0.47,
                     cached_tokens=0)
        tracer.event("finish", tid, service="scheduler", reason="stop",
                     output_tokens=8, preemptions=0)

        clock = FakeClock()
        plane = IncidentPlane(
            IncidentConfig(
                dir=str(tmp_path), keep=4, min_interval_s=30.0,
                detector=DetectorConfig(min_window_count=4, baseline_checks=2,
                                        debounce_s=10.0),
            ),
            config_probe=lambda: {"engine": "synthetic"},
            clock=clock,
        )
        calm = stats_with(queue_wait=[0.001] * 8, ttft=[0.01] * 8)
        for i in range(3):
            clock.t = float(i + 1)
            assert plane.observe(calm) == []
        clock.t = 10.0
        spike = stats_with(queue_wait=[0.45] * 8, ttft=[0.47] * 8)
        fired = plane.observe(spike)
        assert fired == ["ttft_p99", "queue_wait_p99"], fired

        # BOTH signals fired but the global rate limit collapses them to
        # ONE bundle — whose detector snapshot carries both signals'
        # evidence, so attribution is unaffected by which wrote first.
        bundles = sorted(glob.glob(str(tmp_path / "incident_*.json")))
        assert len(bundles) == 1

        bundle = autopsy.load_bundle(bundles[0])
        assert bundle is not None and bundle["schema"] == BUNDLE_SCHEMA
        assert bundle["trace_ring"], "bundle lost the trace ring"
        assert bundle["thread_stacks"], "bundle lost the thread stacks"
        assert bundle["config"] == {"engine": "synthetic"}

        report = autopsy.incident_report(bundle)
        # queue_wait jumped 450x vs ttft's 47x: attribution must pick the
        # injected phase even though ttft fired first.
        assert report["attribution"] == "queue_wait"
        assert report["signal_ratios"]["queue_wait_p99"] > report["signal_ratios"]["ttft_p99"]

        req = autopsy.request_report(bundle["trace_ring"], tid, bundle=bundle)
        assert req["attribution"] == "queue_wait"
        assert req["phases_ms"]["queue_wait"] == pytest.approx(450.0)
        assert req["finish_reason"] == "stop"
        # Fleet context: the request's 450 ms queue wait sits at the top of
        # the captured window distribution.
        assert "queue_wait" in req["fleet_context"]
    finally:
        configure_tracing(path=None, sample=0.0, ring_size=0)


def test_autopsy_and_trace_view_cli_on_bundle(tmp_path):
    """Both CLIs accept a bundle file directly."""
    configure_tracing(path=None, sample=1.0, ring_size=64, service="test")
    try:
        tracer = get_tracer()
        tid = "ab" * 16
        tracer.event("queued", tid, service="scheduler", prompt_tokens=4)
        tracer.event("admitted", tid, service="scheduler", queue_s=0.2)
        tracer.event("first_token", tid, service="scheduler", ttft_s=0.25)
        tracer.event("finish", tid, service="scheduler", reason="stop", output_tokens=2)
        rec = IncidentRecorder(dir=str(tmp_path), min_interval_s=0.0)
        path = rec.capture(
            "queue_wait_p99", {"value": 0.2, "baseline": 0.001},
            {"stats": {}, "trace_ring": tracer.ring_records(),
             "detector": {"last_values": {"queue_wait_p99": 0.2},
                          "baselines": {"queue_wait_p99": 0.001}}},
        )
    finally:
        configure_tracing(path=None, sample=0.0, ring_size=0)

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "autopsy.py"), path, "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["attribution"] == "queue_wait"

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "autopsy.py"), path,
         "--request", tid],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "QUEUE_WAIT" in out.stdout

    for argv in (
        [sys.executable, os.path.join(REPO, "tools", "trace_view.py"), path],
        [sys.executable, os.path.join(REPO, "tools", "trace_view.py"), path,
         "--request", tid],
    ):
        proc = subprocess.run(argv, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert tid in proc.stdout


# --- tail-based sampling ------------------------------------------------------

def _unsampled_id(tracer, start: int = 0) -> str:
    for i in range(start, start + 10000):
        tid = f"{i:032x}"
        if not tracer.sampled(tid):
            return tid
    raise AssertionError("no unsampled id found")


def test_tail_sampling_keeps_promoted_spans(tmp_path):
    """sample=0.01 + tail: an unsampled trace's spans stay out of the
    export until promote(), then land complete; promote is idempotent."""
    path = str(tmp_path / "trace.jsonl")
    tracer = configure_tracing(path=path, sample=0.01, ring_size=128, tail=True,
                               service="test")
    try:
        tid = _unsampled_id(tracer)
        assert not tracer.sampled(tid) and tracer.record_allowed(tid)
        span = tracer.span("http_request", tid, model="m")
        tracer.event("queued", tid, service="scheduler")
        tracer.event("first_token", tid, service="scheduler", ttft_s=0.5)
        span.end()
        tracer.flush()
        assert not os.path.exists(path) or not [
            r for r in _read(path) if r["trace_id"] == tid
        ], "unsampled trace leaked into the export before promotion"

        assert tracer.promote(tid) == 3
        tracer.flush()
        names = {r["name"] for r in _read(path) if r["trace_id"] == tid}
        assert names == {"http_request", "queued", "first_token"}
        # Idempotent: already-promoted records do not double-export.
        assert tracer.promote(tid) == 0
    finally:
        configure_tracing(path=None, sample=0.0, ring_size=0)


def _read(path):
    from dynamo_tpu.runtime.tracing import read_trace_file

    return read_trace_file(path)


async def test_tail_sampling_http_promotes_slo_violators(tmp_path):
    """HTTP service at sample rate 0.01 with tail keep: a request that
    violates its (absurdly tight) SLO keeps its full span set in the
    export; the sampling decision alone would have dropped it."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.telemetry import SloConfig

    path = str(tmp_path / "trace.jsonl")
    tracer = configure_tracing(path=path, sample=0.01, ring_size=512, tail=True,
                               service="test")
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", eos_token_ids=[0],
            scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64],
                                      decode_buckets=[1, 2, 4]),
        )
    )
    manager = ModelManager()
    manager.add_model("chat", "tiny-tail", build_local_pipeline(ByteTokenizer(), engine))
    # 0.001 ms TTFT target: every real request violates → every request's
    # trace is promoted regardless of the 1% head-sampling rate.
    service = HttpService(manager, host="127.0.0.1", port=0,
                          slo=SloConfig(ttft_ms=0.001))
    await service.start()
    try:
        tid = _unsampled_id(tracer, start=50000)
        headers = {"traceparent": f"00-{tid}-{'cd' * 8}-01"}
        body = {"model": "tiny-tail",
                "messages": [{"role": "user", "content": "slow request"}],
                "max_tokens": 4, "temperature": 0}
        async with aiohttp.ClientSession() as s:
            async with s.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions",
                json=body, headers=headers,
            ) as r:
                assert r.status == 200, await r.text()
    finally:
        await service.stop()
        await engine.stop()
    tracer.flush()
    records = [r for r in _read(path) if r["trace_id"] == tid]
    configure_tracing(path=None, sample=0.0, ring_size=0)
    names = {r["name"] for r in records}
    assert "http_request" in names, f"violating request lost its spans: {names}"
    # The engine-side lifecycle rode along too (same process, same ring).
    assert {"queued", "first_token", "finish"} <= names, names


# --- e2e: synthetic spike through the demo stack -----------------------------

async def test_e2e_spike_one_bundle_attributed_to_queue_wait(tmp_path):
    """frontend → push_router → worker wire path → scheduler: calm traffic
    builds the detector baseline, a concurrency burst against max_running=2
    injects a queue-wait spike, and the scrape-driven detector captures
    exactly ONE debounced bundle whose autopsy attributes queue wait."""
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_routed_pipeline, register_llm
    from dynamo_tpu.llm.http.service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.push_router import PushRouter

    incident_dir = str(tmp_path / "incidents")
    # Ring-only tracing: the bundle's trace ring is the only trace sink.
    configure_tracing(path=None, sample=1.0, ring_size=1024, service="test")
    drt = await DistributedRuntime.detached()
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32", eos_token_ids=[0],
            scheduler=SchedulerConfig(
                num_blocks=128, max_running=2,
                prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
                # Phase-separated steps only: the injected anomaly must be
                # queueing, with no mixed-shape compiles muddying the water.
                enable_mixed_batching=False,
            ),
            # Cover the burst's grown block tables (≈40 prompt + 32 output
            # tokens) so steady state has no mid-traffic compiles.
            warmup_ctx=128,
            incident_dir=incident_dir,
        )
    )
    # Deterministic-for-CI thresholds: a calm-phase fire needs a 50 ms
    # excursion (not CI noise), debounce/rate-limit far beyond the test
    # duration so a persistent spike yields exactly one bundle.
    engine.incidents.detector.config = DetectorConfig(
        jump_factor=3.0, min_abs_s=0.05, min_window_count=6,
        baseline_checks=3, debounce_s=600.0,
    )
    engine.incidents.recorder.min_interval_s = 600.0

    service = None
    try:
        ep = drt.namespace("incidenttest").component("backend").endpoint("generate")
        card = ModelDeploymentCard(name="tiny-incident", model_type="chat")
        handle, _ = await register_llm(drt, ep, engine, card,
                                       stats_handler=engine.stats_handler)
        # Force the real wire path (pub/sub + TCP call-home).
        drt.local_engines.pop(handle.instance.instance_id)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        manager = ModelManager()
        manager.add_model("chat", "tiny-incident",
                          build_routed_pipeline(ByteTokenizer(), PushRouter(client), card))
        service = HttpService(manager, host="127.0.0.1", port=0)
        await service.start()

        async def post(session, i, tokens):
            body = {"model": "tiny-incident",
                    "messages": [{"role": "user", "content": f"req {i}"}],
                    "max_tokens": tokens, "temperature": 0}
            async with session.post(
                f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body
            ) as r:
                assert r.status == 200, await r.text()
                await r.json()

        async with aiohttp.ClientSession() as session:
            # Calm phase: sequential requests, scrape (detector check) after
            # each — builds + arms the queue-wait/ttft baselines over the
            # REAL scrape wire.
            for i in range(8):
                await post(session, i, 4)
                await client.scrape_stats()
            stats = await client.scrape_stats()
            w = next(iter(stats.values()))
            assert w["incidents_total"] == 0, "detector fired on calm traffic"

            # Spike: a 24-way burst against 2 decode slots — the tail of
            # the burst queues for hundreds of ms (the injected phase).
            await asyncio.gather(*(post(session, 100 + i, 32) for i in range(24)))
            for _ in range(3):  # several scrapes: debounce must hold at one
                stats = await client.scrape_stats()

        w = next(iter(stats.values()))
        assert w["incidents_total"] == 1, f"expected exactly one capture: {w['incidents_total']}"
        assert w["incident_last_age_s"] >= 0.0
        # /debug/state surfaces the incident list (satellite).
        info = engine.debug_state()["incidents"]
        assert len(info["bundles"]) == 1
        assert info["bundles"][0]["status"] == "written"
        assert info["last_capture"]["path"]
        # Steady state stayed compile-free: the spike was queueing, not XLA.
        assert w["compiles_after_warmup_total"] == 0
    finally:
        if service is not None:
            await service.stop()
        await engine.stop()
        await drt.shutdown()
        configure_tracing(path=None, sample=0.0, ring_size=0)

    bundles = sorted(glob.glob(os.path.join(incident_dir, "incident_*.json")))
    assert len(bundles) == 1, f"expected exactly one bundle: {bundles}"
    bundle = autopsy.load_bundle(bundles[0])
    assert bundle is not None
    report = autopsy.incident_report(bundle)
    assert report["attribution"] == "queue_wait", json.dumps(report, indent=2)[:2000]
    # The bundle is self-contained evidence: digests, step ring, stacks,
    # config, trace ring all present.
    assert report["digests"]["queue_wait"]["count"] > 0
    assert bundle["flight"]["recent_steps"]
    assert bundle["thread_stacks"]
    assert bundle["config"]["scheduler"]["max_running"] == 2
    assert bundle["trace_ring"], "ring-only tracing did not reach the bundle"
    # A spiked request from the ring attributes to queue wait too.
    finishes = [r for r in bundle["trace_ring"] if r.get("name") == "admitted"
                and (r.get("attrs") or {}).get("queue_s", 0) > 0.05]
    assert finishes, "no queued request recorded in the trace ring"
    req = autopsy.request_report(bundle["trace_ring"], finishes[-1]["trace_id"],
                                 bundle=bundle)
    assert req.get("phases_ms", {}).get("queue_wait", 0) > 50.0


# --- stats-key parity (engine-free planner stacks) ----------------------------

def test_mocker_emits_identical_incident_keys():
    from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine

    mocker = MockTpuEngine(MockEngineArgs())
    stats = mocker.stats_handler()
    expected = {"incidents_total", "incident_last_age_s", "profiler_captures_total"}
    expected |= {f"incidents_{r}_total" for r in REASONS}
    missing = expected - set(stats)
    assert not missing, f"mocker stats missing incident keys: {missing}"
    assert stats["incidents_total"] == 0
    assert stats["incident_last_age_s"] == -1.0


# --- on-demand profiling ------------------------------------------------------

def test_host_stack_sampler_attributes_dynamo_frames():
    import threading
    import time as _time

    from dynamo_tpu.runtime.profiling import HostStackSampler

    stop = threading.Event()

    def busy():
        # A thread burning time inside dynamo_tpu code: LatencyDigest
        # observes give the sampler real frames to attribute.
        d = LatencyDigest()
        while not stop.is_set():
            for i in range(2000):
                d.observe(0.001 * (1 + i % 7))

    t = threading.Thread(target=busy, name="busy-digest", daemon=True)
    t.start()
    try:
        sampler = HostStackSampler(interval_s=0.002)
        report = sampler.sample_for(0.4)
    finally:
        stop.set()
        t.join(timeout=2)
    assert report["samples"] > 20
    assert report["top"], "no frames attributed"
    assert any("telemetry.py" in f["frame"] for f in report["top"]), report["top"]


async def test_debug_profile_route(tmp_path):
    from dynamo_tpu.runtime.config import SystemConfig
    from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer
    from dynamo_tpu.runtime.profiling import DeviceProfiler

    health = SystemHealth()
    health.set_system_ready()
    server = SystemStatusServer(
        health,
        config=SystemConfig(enabled=True, port=0, host="127.0.0.1"),
        profiler=DeviceProfiler(out_dir=str(tmp_path / "profiles")),
    )
    await server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        async with aiohttp.ClientSession() as s:
            # Host stack sampling: always available, returns a frame report.
            async with s.post(f"{base}/debug/profile?seconds=0.2&kind=host") as r:
                assert r.status == 200
                rep = await r.json()
                assert rep["kind"] == "host" and rep["samples"] > 0
            # Device capture: jax.profiler runs on CPU too.
            async with s.post(f"{base}/debug/profile?seconds=0.2") as r:
                rep = await r.json()
                assert r.status == 200, rep
                assert rep["kind"] == "device" and rep["status"] == "ok"
                assert os.path.isdir(rep["path"])
            # Validation: bad/oversized windows are 400s, not crashes.
            async with s.post(f"{base}/debug/profile?seconds=nope") as r:
                assert r.status == 400
            async with s.post(f"{base}/debug/profile?seconds=900") as r:
                assert r.status == 400
    finally:
        await server.stop()
