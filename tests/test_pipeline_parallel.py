"""Pipeline parallelism: pipelined decode must match the single-stack decode.

Reference analogue: trtllm `pipeline_parallel_size` passthrough (SURVEY.md
§2e) — here PP is native (engine/pipeline_parallel.py), so the test checks
numerical equivalence of the microbatched ppermute pipeline against the
plain `llama.decode` on the same paged cache state, on a CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.pipeline_parallel import pipelined_decode
from dynamo_tpu.engine.sharding import (
    ParallelConfig,
    build_mesh,
    kv_cache_spec,
    param_specs,
    shard_params,
)


def _setup(cfg, batch, seed=0):
    params = llama.init_params(cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
    cache = KvCacheArrays.create(cfg, num_blocks=batch * 4 + 2, dtype=jnp.float32)
    rng = np.random.default_rng(seed)
    # Each row decodes at a distinct position with its own block table.
    positions = jnp.array(rng.integers(1, 2 * cfg.block_size, size=batch), dtype=jnp.int32)
    max_blocks = 4
    tables = jnp.array(
        1 + np.arange(batch * max_blocks).reshape(batch, max_blocks) % (batch * 4), dtype=jnp.int32
    )
    tokens = jnp.array(rng.integers(0, cfg.vocab_size, size=batch), dtype=jnp.int32)
    active = jnp.array([True] * (batch - 1) + [False])
    return params, cache, tokens, positions, tables, active


@pytest.mark.parametrize("pp,tp,mbs", [(2, 1, 2), (4, 1, 4), (2, 2, 4), (4, 2, 4)])
def test_pipelined_decode_matches_dense(pp, tp, mbs):
    cfg = get_config("tiny").replace(num_layers=4)
    assert cfg.num_layers % pp == 0
    B = 8
    params, cache, tokens, positions, tables, active = _setup(cfg, B)

    ref_logits, ref_k, ref_v = llama.decode(
        params, cfg, cache.k, cache.v, tokens, positions, tables, active
    )

    mesh = build_mesh(ParallelConfig(pp=pp, tp=tp))
    sp = shard_params(params, mesh, cfg.tie_word_embeddings, pp=True)
    ksh = jax.device_put(cache.k, NamedSharding(mesh, kv_cache_spec(cfg.num_kv_heads, tp, pp=True)))
    vsh = jax.device_put(cache.v, NamedSharding(mesh, kv_cache_spec(cfg.num_kv_heads, tp, pp=True)))

    logits, k_new, v_new = jax.jit(
        lambda p, k, v: pipelined_decode(
            p, cfg, k, v, tokens, positions, tables, active, mesh, num_microbatches=mbs
        )
    )(sp, ksh, vsh)

    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4)
    # Skip scratch block 0: duplicate-index scatters land there with
    # unspecified ordering, so its contents are not comparable.
    np.testing.assert_allclose(np.asarray(k_new[:, 1:]), np.asarray(ref_k[:, 1:]), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(v_new[:, 1:]), np.asarray(ref_v[:, 1:]), rtol=1e-5, atol=1e-5)


def test_param_specs_pp_layer_axis():
    specs = param_specs(tie_word_embeddings=True, pp=True)
    assert specs["layers"]["wq"][0] == "pp"
    assert specs["embed"][0] == "tp"
    assert kv_cache_spec(4, 2, pp=True)[0] == "pp"
