"""Token determinism across KVBM offload/onboard cycles under real engine
traffic (ref: tests/kvbm/test_determinism.py, 1,113 LoC of the same
guarantee): a prompt answered from a G2-onboarded prefix must produce
exactly the tokens the G1-cached path produced, and the async offload
queue must actually exercise (offloads and onboards both observed).

Also covers the async-offload snapshot ordering: eviction queues a
device-side copy and the block is reused immediately — if the snapshot
raced the reuse, onboarded KV would be garbage and outputs would diverge.
"""

import asyncio

import pytest

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.runtime.engine import Context


def build_engine(num_blocks, host_blocks):
    return TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            kvbm_host_blocks=host_blocks,
            scheduler=SchedulerConfig(
                num_blocks=num_blocks,
                max_running=4,
                prefill_buckets=[16, 32, 64],
                decode_buckets=[1, 2, 4],
                num_scheduler_steps=1,
            ),
        )
    )


async def gen(engine, tokens, mt=12):
    out = []
    req = {
        "token_ids": tokens,
        "sampling_options": {"temperature": 0.0},
        "stop_conditions": {"max_tokens": mt},
    }
    async for fr in engine.generate(req, Context()):
        out.extend(fr["token_ids"])
    return out


def test_offload_onboard_cycle_is_token_deterministic():
    async def main():
        # G1 small enough that churn traffic evicts the probe's blocks.
        engine = build_engine(num_blocks=24, host_blocks=64)
        kvbm = engine.scheduler.kvbm
        assert kvbm is not None

        probe = list(range(40, 72))  # 32 tokens = 2 full blocks
        out_fresh = await gen(engine, probe)
        out_g1 = await gen(engine, probe)  # G1 prefix hit
        assert out_fresh == out_g1

        # Churn: enough distinct traffic to evict the probe's cached blocks.
        for i in range(12):
            await gen(engine, [200 + i] + list(range(i * 7 + 1, i * 7 + 29)), mt=4)
        kvbm.flush_pending()
        assert kvbm.metrics.offloads_g2 > 0, "eviction churn produced no offloads"

        out_onboard = await gen(engine, probe)
        assert kvbm.metrics.onboards_g2 > 0, "probe re-run did not onboard from G2"
        assert out_onboard == out_g1, (
            "offload/onboard cycle changed greedy output: "
            f"{out_g1} vs {out_onboard}"
        )
        await engine.stop()

    asyncio.run(main())


def test_mixed_traffic_determinism_across_cycles():
    """100 mixed requests over a churning cache: every repeated prompt must
    reproduce its first answer exactly, whatever tier its prefix came from."""

    async def main():
        engine = build_engine(num_blocks=14, host_blocks=128)
        kvbm = engine.scheduler.kvbm
        # 36-token prompts = 2 full cacheable blocks each; 10 prompts want 20
        # cached blocks in a 14-block pool, so rounds constantly evict and
        # re-onboard each other's prefixes.
        prompts = [list(range(10 + 3 * i, 46 + 3 * i)) for i in range(10)]
        first = {}
        for round_ in range(10):
            for i, p in enumerate(prompts):
                out = await gen(engine, p, mt=6)
                if i in first:
                    assert out == first[i], (
                        f"prompt {i} diverged on round {round_}: {first[i]} vs {out}"
                    )
                else:
                    first[i] = out
        kvbm.flush_pending()
        assert kvbm.metrics.offloads_g2 > 0
        assert kvbm.metrics.onboards_g2 > 0
        await engine.stop()

    asyncio.run(main())
