"""MoE model tests: routing actually selects experts, sparse dispatch parity
(ragged grouped-GEMM + capacity-factor) vs dense, FLOPs scaling with top-k K
rather than expert count E, paged decode parity, and expert-parallel sharding
on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sharding import ParallelConfig, build_mesh, kv_cache_spec, shard_params

CFG = get_config("tiny-moe").replace(dtype="float32")


def test_moe_mlp_uses_topk_experts():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()}
    x = jax.random.normal(jax.random.PRNGKey(1), (8, CFG.hidden_size), dtype=jnp.float32)
    out = llama._mlp(x, lp, CFG)
    assert out.shape == x.shape

    # Routing must matter: zeroing the top experts' weights changes output.
    lp2 = dict(lp)
    lp2["w_down"] = jnp.zeros_like(lp["w_down"])
    out2 = llama._mlp(x, lp2, CFG)
    assert not np.allclose(np.asarray(out), np.asarray(out2))

    # Combine weights are normalized: uniform expert outputs pass through.
    lp3 = dict(lp)
    lp3["w_gate"] = jnp.broadcast_to(lp["w_gate"][0:1], lp["w_gate"].shape)
    lp3["w_up"] = jnp.broadcast_to(lp["w_up"][0:1], lp["w_up"].shape)
    lp3["w_down"] = jnp.broadcast_to(lp["w_down"][0:1], lp["w_down"].shape)
    ref_single = (jax.nn.silu(x @ lp["w_gate"][0]) * (x @ lp["w_up"][0])) @ lp["w_down"][0]
    out3 = llama._mlp(x, lp3, CFG)
    np.testing.assert_allclose(np.asarray(out3), np.asarray(ref_single), rtol=1e-5, atol=1e-5)


def test_moe_prefill_decode_consistent():
    """Prefill then decode one token ≡ prefill of the extended sequence."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    prompt = list(range(20, 36))

    logits, k, v = llama.prefill(
        params, CFG, cache.k, cache.v, jnp.array(prompt, dtype=jnp.int32), jnp.int32(16), jnp.int32(0), table
    )
    nxt = int(jnp.argmax(logits))

    toks = jnp.array([nxt, 0], dtype=jnp.int32)
    pos = jnp.array([16, 0], dtype=jnp.int32)
    tables = jnp.zeros((2, 4), dtype=jnp.int32).at[0].set(table)
    active = jnp.array([True, False])
    dec_logits, _, _ = llama.decode(params, CFG, k, v, toks, pos, tables, active)

    cache2 = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    ext = prompt + [nxt]
    padded = jnp.array(ext + [0] * (32 - len(ext)), dtype=jnp.int32)
    full_logits, _, _ = llama.prefill(
        params, CFG, cache2.k, cache2.v, padded, jnp.int32(len(ext)), jnp.int32(0), table
    )
    np.testing.assert_allclose(np.asarray(dec_logits[0]), np.asarray(full_logits), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("ep,tp", [(2, 1), (4, 2)])
def test_moe_expert_parallel_matches_single_device(ep, tp):
    mesh = build_mesh(ParallelConfig(ep=ep, tp=tp))
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    tokens = jnp.arange(10, 26, dtype=jnp.int32)

    ref_logits, _, _ = llama.prefill(
        params, CFG, cache.k, cache.v, tokens, jnp.int32(16), jnp.int32(0), table
    )

    sp = shard_params(params, mesh, CFG.tie_word_embeddings, CFG.num_experts)
    cache_sharding = NamedSharding(mesh, kv_cache_spec(CFG.num_kv_heads, tp))
    k_sh = jax.device_put(jnp.zeros_like(cache.k), cache_sharding)
    v_sh = jax.device_put(jnp.zeros_like(cache.v), cache_sharding)
    logits, _, _ = jax.jit(
        lambda p, k, v: llama.prefill(p, CFG, k, v, tokens, jnp.int32(16), jnp.int32(0), table)
    )(sp, k_sh, v_sh)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4)


def _mk_moe_inputs(E, K, T=16, D=32, F=48, seed=0, dtype=jnp.float32):
    cfg = CFG.replace(num_experts=E, num_experts_per_tok=K, hidden_size=D, intermediate_size=F)
    keys = jax.random.split(jax.random.PRNGKey(seed), 5)
    lp = {
        "router": jax.random.normal(keys[0], (D, E), dtype=dtype) * 0.5,
        "w_gate": jax.random.normal(keys[1], (E, D, F), dtype=dtype) * D**-0.5,
        "w_up": jax.random.normal(keys[2], (E, D, F), dtype=dtype) * D**-0.5,
        "w_down": jax.random.normal(keys[3], (E, F, D), dtype=dtype) * F**-0.5,
    }
    x = jax.random.normal(keys[4], (T, D), dtype=dtype)
    return cfg, lp, x


@pytest.mark.parametrize("E,K", [(4, 2), (8, 3)])
def test_moe_ragged_matches_dense(E, K):
    cfg, lp, x = _mk_moe_inputs(E, K)
    ref = llama._moe_dense(x, lp, cfg)
    out = llama._moe_ragged(x, lp, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_moe_ragged_matches_dense_bf16():
    cfg, lp, x = _mk_moe_inputs(8, 2, dtype=jnp.bfloat16)
    ref = llama._moe_dense(x, lp, cfg)
    out = llama._moe_ragged(x, lp, cfg)
    np.testing.assert_allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref, dtype=np.float32), rtol=0.1, atol=0.05
    )


def test_moe_capacity_matches_dense_when_no_drops():
    # capacity_factor = E/K ⇒ C = T ⇒ no token can overflow.
    E, K = 8, 2
    cfg, lp, x = _mk_moe_inputs(E, K)
    cfg = cfg.replace(moe_capacity_factor=E / K)
    ref = llama._moe_dense(x, lp, cfg)
    out, dropped = llama._moe_capacity(x, lp, cfg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert int(dropped) == 0


def test_moe_capacity_drops_overflow_to_residual():
    """With capacity 1 slot/expert, overflowing assignments contribute zero
    (the MLP output is the residual-only fallback), and nothing crashes."""
    E, K = 4, 2
    cfg, lp, x = _mk_moe_inputs(E, K, T=16)
    cfg = cfg.replace(moe_capacity_factor=E / (16 * K))  # C = 1
    out, dropped = llama._moe_capacity(x, lp, cfg)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    # Drop counter reports the overflow: 16 tokens * K=2 wanted, 4 slots kept.
    assert int(dropped) == 16 * K - 4
    # Strictly fewer kept assignments than the no-drop run ⇒ smaller norm.
    full, d_full = llama._moe_capacity(x, lp, cfg.replace(moe_capacity_factor=E / K))
    assert int(d_full) == 0
    assert np.linalg.norm(np.asarray(out)) < np.linalg.norm(np.asarray(full))


def test_moe_sparse_flops_scale_with_k_not_e():
    """The VERDICT criterion: per-token expert FLOPs must scale with top-k K,
    not expert count E.

    The ragged path's work is T*K expert-GEMM rows by construction (xs has
    exactly T*K rows whatever E is); on the CPU *test* backend XLA lowers
    ragged_dot as a per-group decomposition whose cost_analysis reports
    E-proportional flops, so the strict E-independence assertion here uses
    shape math + a relative bound vs dense, and the lowering-independent
    einsum assertion lives in test_moe_capacity_flops_scale_with_k_not_e."""

    def flops(fn, *args):
        c = jax.jit(fn).lower(*args).compile().cost_analysis()
        return c["flops"] if isinstance(c, dict) else c[0]["flops"]

    T, D, F, K = 64, 32, 48, 2
    cfg_small, lp_small, x = _mk_moe_inputs(8, K, T=T, D=D, F=F)
    cfg_big, lp_big, _ = _mk_moe_inputs(32, K, T=T, D=D, F=F)

    dense_small = flops(lambda lp, x: llama._moe_dense(x, lp, cfg_small), lp_small, x)
    dense_big = flops(lambda lp, x: llama._moe_dense(x, lp, cfg_big), lp_big, x)

    assert dense_big / dense_small > 3.0, "dense baseline should scale with E"

    # Lowering-independent guarantee: the expert GEMMs consume a row buffer
    # of exactly T*K rows regardless of E — inspect the jaxpr for the
    # ragged_dot operands. (cost_analysis is NOT usable for this on the CPU
    # test backend: its reference decomposition pads every group to the full
    # row range, reporting E-proportional flops; the TPU Mosaic grouped-GEMM
    # kernel computes true ragged row counts.)
    for cfg_i, lp_i in ((cfg_small, lp_small), (cfg_big, lp_big)):
        jaxpr = jax.make_jaxpr(lambda lp, x: llama._moe_ragged(x, lp, cfg_i))(lp_i, x)
        ragged_eqns = [e for e in jaxpr.jaxpr.eqns if "ragged" in e.primitive.name]
        assert len(ragged_eqns) == 3, "expected 3 grouped GEMMs (gate/up/down)"
        for e in ragged_eqns:
            assert e.invars[0].aval.shape[0] == T * K, (
                f"expert GEMM rows must be T*K={T * K}, got {e.invars[0].aval.shape[0]}"
            )


def test_moe_capacity_flops_scale_with_k_not_e():
    def flops(fn, *args):
        c = jax.jit(fn).lower(*args).compile().cost_analysis()
        return c["flops"] if isinstance(c, dict) else c[0]["flops"]

    T, D, F, K = 64, 32, 48, 2
    cfg_small, lp_small, x = _mk_moe_inputs(8, K, T=T, D=D, F=F)
    cfg_big, lp_big, _ = _mk_moe_inputs(32, K, T=T, D=D, F=F)
    cap_small = flops(lambda lp, x: llama._moe_capacity(x, lp, cfg_small), lp_small, x)
    cap_big = flops(lambda lp, x: llama._moe_capacity(x, lp, cfg_big), lp_big, x)
    dense_big = flops(lambda lp, x: llama._moe_dense(x, lp, cfg_big), lp_big, x)
    # Expert-GEMM FLOPs are fixed at cf*K*T*D*F; dispatch one-hots add E-
    # proportional but tiny terms. Allow 2x slack, require win over dense.
    assert cap_big / cap_small < 2.0
    assert cap_big < 0.6 * dense_big


@pytest.mark.parametrize("dispatch", ["ragged", "capacity"])
def test_moe_prefill_sparse_matches_dense_e2e(dispatch):
    """Full prefill forward with sparse dispatch ≡ dense dispatch."""
    cfg_d = CFG.replace(moe_dispatch="dense")
    cfg_s = CFG.replace(moe_dispatch=dispatch, moe_capacity_factor=CFG.num_experts / CFG.num_experts_per_tok)
    params = llama.init_params(cfg_d, jax.random.PRNGKey(0), dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    tokens = jnp.arange(10, 26, dtype=jnp.int32)

    cache = KvCacheArrays.create(cfg_d, 16, dtype=jnp.float32)
    ref, _, _ = llama.prefill(params, cfg_d, cache.k, cache.v, tokens, jnp.int32(16), jnp.int32(0), table)
    cache2 = KvCacheArrays.create(cfg_s, 16, dtype=jnp.float32)
    out, _, _ = llama.prefill(params, cfg_s, cache2.k, cache2.v, tokens, jnp.int32(16), jnp.int32(0), table)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_moe_capacity_expert_parallel_on_mesh():
    """Capacity dispatch under a 4-way ep mesh ≡ dense on one device — the
    wide-EP serving configuration (VERDICT r2 #2)."""
    ep = 4
    mesh = build_mesh(ParallelConfig(ep=ep))
    cfg = CFG.replace(moe_dispatch="capacity",
                      moe_capacity_factor=CFG.num_experts / CFG.num_experts_per_tok)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    tokens = jnp.arange(10, 26, dtype=jnp.int32)

    cache = KvCacheArrays.create(cfg, 16, dtype=jnp.float32)
    ref, _, _ = llama.prefill(
        params, cfg.replace(moe_dispatch="dense"), cache.k, cache.v,
        tokens, jnp.int32(16), jnp.int32(0), table,
    )

    sp = shard_params(params, mesh, cfg.tie_word_embeddings, cfg.num_experts)
    cache_sharding = NamedSharding(mesh, kv_cache_spec(cfg.num_kv_heads, 1))
    k_sh = jax.device_put(jnp.zeros_like(cache.k), cache_sharding)
    v_sh = jax.device_put(jnp.zeros_like(cache.v), cache_sharding)
    logits, _, _ = jax.jit(
        lambda p, k, v: llama.prefill(p, cfg, k, v, tokens, jnp.int32(16), jnp.int32(0), table)
    )(sp, k_sh, v_sh)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_moe_capacity_inactive_lanes_cannot_steal_slots():
    """Decode batches carry padded/finished lanes; with capacity dispatch the
    dead lanes (all embedding token 0, identical routing) must not consume
    expert slots ahead of live tokens. The live lane sits at the HIGHEST
    batch index — without the valid mask, identical dead lanes at lower
    indices exhaust C and drop it to residual."""
    E, K, T = 4, 2, 16
    cfg, lp, _ = _mk_moe_inputs(E, K, T=T)
    cfg = cfg.replace(moe_capacity_factor=1.0)  # C = 8: dead lanes could fill it
    keys = jax.random.split(jax.random.PRNGKey(9), 2)
    live = jax.random.normal(keys[0], (1, cfg.hidden_size), dtype=jnp.float32)
    dead = jnp.broadcast_to(jax.random.normal(keys[1], (1, cfg.hidden_size)), (T - 1, cfg.hidden_size))
    x = jnp.concatenate([dead, live], axis=0)  # live token last
    valid = jnp.zeros((T,), dtype=bool).at[T - 1].set(True)

    out_masked, dropped = llama._moe_capacity(x, lp, cfg, valid=valid)
    assert int(dropped) == 0  # dead lanes are not live assignments
    # Reference: live token alone (no contention at all).
    ref, _ = llama._moe_capacity(live, lp, cfg.replace(moe_capacity_factor=E / K))
    np.testing.assert_allclose(np.asarray(out_masked[-1]), np.asarray(ref[0]), rtol=1e-5, atol=1e-5)
    # And the dead lanes contribute nothing.
    np.testing.assert_allclose(np.asarray(out_masked[:-1]), 0.0, atol=1e-6)


def test_moe_counters_drain_on_direct_read():
    """moe_dropped_total / moe_assignments_total are drained-on-read
    properties: jitted steps stage aux scalars in _pending_aux (no per-step
    host sync), so a direct reader — not just metrics() — must see them
    (regression: stale counters for anyone bypassing metrics())."""
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig

    cfg = CFG.replace(moe_dispatch="capacity")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    sched = Scheduler(cfg, params, SchedulerConfig(num_blocks=16), dtype=jnp.float32)
    assert sched._moe_stats

    sched._pending_aux.append((jnp.int32(3), jnp.int32(40)))
    sched._pending_aux.append((jnp.int32(2), jnp.int32(24)))
    assert sched.moe_dropped_total == 5
    assert sched.moe_assignments_total == 64
    assert not sched._pending_aux  # drained, not double-counted
    assert sched.moe_dropped_total == 5

    m = sched.metrics()
    assert m.moe_dropped_total == 5 and m.moe_assignments_total == 64
