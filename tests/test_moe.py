"""MoE model tests: routing actually selects experts, paged decode parity,
and expert-parallel sharding on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sharding import ParallelConfig, build_mesh, kv_cache_spec, shard_params

CFG = get_config("tiny-moe").replace(dtype="float32")


def test_moe_mlp_uses_topk_experts():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()}
    x = jax.random.normal(jax.random.PRNGKey(1), (8, CFG.hidden_size), dtype=jnp.float32)
    out = llama._mlp(x, lp, CFG)
    assert out.shape == x.shape

    # Routing must matter: zeroing the top experts' weights changes output.
    lp2 = dict(lp)
    lp2["w_down"] = jnp.zeros_like(lp["w_down"])
    out2 = llama._mlp(x, lp2, CFG)
    assert not np.allclose(np.asarray(out), np.asarray(out2))

    # Combine weights are normalized: uniform expert outputs pass through.
    lp3 = dict(lp)
    lp3["w_gate"] = jnp.broadcast_to(lp["w_gate"][0:1], lp["w_gate"].shape)
    lp3["w_up"] = jnp.broadcast_to(lp["w_up"][0:1], lp["w_up"].shape)
    lp3["w_down"] = jnp.broadcast_to(lp["w_down"][0:1], lp["w_down"].shape)
    ref_single = (jax.nn.silu(x @ lp["w_gate"][0]) * (x @ lp["w_up"][0])) @ lp["w_down"][0]
    out3 = llama._mlp(x, lp3, CFG)
    np.testing.assert_allclose(np.asarray(out3), np.asarray(ref_single), rtol=1e-5, atol=1e-5)


def test_moe_prefill_decode_consistent():
    """Prefill then decode one token ≡ prefill of the extended sequence."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    prompt = list(range(20, 36))

    logits, k, v = llama.prefill(
        params, CFG, cache.k, cache.v, jnp.array(prompt, dtype=jnp.int32), jnp.int32(16), jnp.int32(0), table
    )
    nxt = int(jnp.argmax(logits))

    toks = jnp.array([nxt, 0], dtype=jnp.int32)
    pos = jnp.array([16, 0], dtype=jnp.int32)
    tables = jnp.zeros((2, 4), dtype=jnp.int32).at[0].set(table)
    active = jnp.array([True, False])
    dec_logits, _, _ = llama.decode(params, CFG, k, v, toks, pos, tables, active)

    cache2 = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    ext = prompt + [nxt]
    padded = jnp.array(ext + [0] * (32 - len(ext)), dtype=jnp.int32)
    full_logits, _, _ = llama.prefill(
        params, CFG, cache2.k, cache2.v, padded, jnp.int32(len(ext)), jnp.int32(0), table
    )
    np.testing.assert_allclose(np.asarray(dec_logits[0]), np.asarray(full_logits), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("ep,tp", [(2, 1), (4, 2)])
def test_moe_expert_parallel_matches_single_device(ep, tp):
    mesh = build_mesh(ParallelConfig(ep=ep, tp=tp))
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    cache = KvCacheArrays.create(CFG, 16, dtype=jnp.float32)
    table = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    tokens = jnp.arange(10, 26, dtype=jnp.int32)

    ref_logits, _, _ = llama.prefill(
        params, CFG, cache.k, cache.v, tokens, jnp.int32(16), jnp.int32(0), table
    )

    sp = shard_params(params, mesh, CFG.tie_word_embeddings, CFG.num_experts)
    cache_sharding = NamedSharding(mesh, kv_cache_spec(CFG.num_kv_heads, tp))
    k_sh = jax.device_put(jnp.zeros_like(cache.k), cache_sharding)
    v_sh = jax.device_put(jnp.zeros_like(cache.v), cache_sharding)
    logits, _, _ = jax.jit(
        lambda p, k, v: llama.prefill(p, CFG, k, v, tokens, jnp.int32(16), jnp.int32(0), table)
    )(sp, k_sh, v_sh)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits), rtol=1e-4, atol=1e-4)
