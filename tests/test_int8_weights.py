"""int8 weight-only quantization (engine/quant.py): numeric parity, the
sharded-safetensors load path (VERDICT r4: multi-shard checkpoints were
untested), and host-side quantize-on-load — the mechanism that fits
Llama-3-8B on a single 16 GiB v5e (bf16 weights alone are 15.0 GiB and
OOM before the first decode step; measured)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.quant import QuantW, params_quantized, quantize_params, wt


def test_quantize_roundtrip_accuracy():
    w = jax.random.normal(jax.random.PRNGKey(0), (4, 32, 48), jnp.float32) * 0.1
    from dynamo_tpu.engine.quant import quantize_weight

    qw = quantize_weight(w)
    back = wt(qw, jnp.float32)
    # per-output-channel int8: worst-case error is half a code step.
    err = jnp.max(jnp.abs(back - w) / jnp.maximum(jnp.max(jnp.abs(w), axis=-2, keepdims=True), 1e-9))
    assert float(err) <= (0.5 / 127.0) * 1.01


def test_dequant_to_bf16_error_within_quant_floor():
    """wt() must dequantize in f32 and only cast the PRODUCT to the compute
    dtype: the error vs the exact f32 product is then pure bf16 output
    rounding (≤ 2^-9 relative), not the compounded ~0.4% that multiplying
    a bf16-rounded scale introduced (regression bound for quant.py)."""
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 96), jnp.float32) * 0.07
    from dynamo_tpu.engine.quant import quantize_weight

    qw = quantize_weight(w)
    exact = qw.q.astype(jnp.float32) * qw.scale  # the true dequant value
    got = wt(qw, jnp.bfloat16).astype(jnp.float32)
    rel = np.abs(np.asarray(got - exact)) / np.maximum(np.abs(np.asarray(exact)), 1e-9)
    # bf16 keeps 8 bits of precision: correct rounding of the f32 product
    # stays within a half-ULP (2^-8 relative). The old bf16×bf16 path
    # measured ~1.7× past this bound (double rounding through the bf16
    # scale — ~0.4% worst-case), so this pins the f32-dequant behavior.
    assert float(rel.max()) <= 2.0 ** -8 * 1.001


def test_quantized_decode_matches_dense_closely():
    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    import copy

    qparams = quantize_params({**params, "layers": dict(params["layers"])})
    assert params_quantized(qparams)
    cache = KvCacheArrays.create(cfg, 16, dtype=jnp.float32)
    tables = jnp.tile(jnp.arange(1, 5, dtype=jnp.int32), (2, 1))
    toks = jnp.array([3, 7], jnp.int32)
    pos = jnp.array([20, 9], jnp.int32)
    act = jnp.ones((2,), bool)
    lg1, _, _ = llama.decode(params, cfg, cache.k, cache.v, toks, pos, tables, act)
    lg2, _, _ = llama.decode(qparams, cfg, cache.k, cache.v, toks, pos, tables, act)
    cos = float(jnp.sum(lg1 * lg2) / (jnp.linalg.norm(lg1) * jnp.linalg.norm(lg2)))
    assert cos > 0.995


def _write_sharded_checkpoint(tmp_path, cfg, rng):
    """Synthesize an HF-style checkpoint split across TWO safetensors shards
    (the layout hub downloads of 8B-class models actually have)."""
    from safetensors.numpy import save_file

    D, H, KVH, HD, I, L, V = (
        cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
        cfg.intermediate_size, cfg.num_layers, cfg.vocab_size,
    )
    tensors = {"model.embed_tokens.weight": rng.standard_normal((V, D), dtype=np.float32) * 0.02,
               "model.norm.weight": np.ones((D,), np.float32)}
    for l in range(L):
        p = f"model.layers.{l}."
        tensors[p + "input_layernorm.weight"] = np.ones((D,), np.float32)
        tensors[p + "post_attention_layernorm.weight"] = np.ones((D,), np.float32)
        tensors[p + "self_attn.q_proj.weight"] = rng.standard_normal((H * HD, D), dtype=np.float32) * 0.05
        tensors[p + "self_attn.k_proj.weight"] = rng.standard_normal((KVH * HD, D), dtype=np.float32) * 0.05
        tensors[p + "self_attn.v_proj.weight"] = rng.standard_normal((KVH * HD, D), dtype=np.float32) * 0.05
        tensors[p + "self_attn.o_proj.weight"] = rng.standard_normal((D, H * HD), dtype=np.float32) * 0.05
        tensors[p + "mlp.gate_proj.weight"] = rng.standard_normal((I, D), dtype=np.float32) * 0.05
        tensors[p + "mlp.up_proj.weight"] = rng.standard_normal((I, D), dtype=np.float32) * 0.05
        tensors[p + "mlp.down_proj.weight"] = rng.standard_normal((D, I), dtype=np.float32) * 0.05
    keys = sorted(tensors)
    half = len(keys) // 2
    save_file({k: tensors[k] for k in keys[:half]},
              os.path.join(tmp_path, "model-00001-of-00002.safetensors"))
    save_file({k: tensors[k] for k in keys[half:]},
              os.path.join(tmp_path, "model-00002-of-00002.safetensors"))
    return tensors


def test_sharded_load_bf16_and_int8(tmp_path):
    from dynamo_tpu.engine.weights import load_checkpoint

    cfg = get_config("tiny")
    rng = np.random.default_rng(7)
    tensors = _write_sharded_checkpoint(str(tmp_path), cfg, rng)

    dense = load_checkpoint(str(tmp_path), cfg, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(dense["layers"]["wq"][1]),
        tensors["model.layers.1.self_attn.q_proj.weight"].T,
        rtol=1e-6,
    )

    qcfg = cfg.replace(weight_dtype="int8")
    quant = load_checkpoint(str(tmp_path), qcfg, dtype=jnp.float32)
    assert isinstance(quant["layers"]["wq"], QuantW)
    # Dequantized weights ≈ original within one int8 code step per channel.
    back = np.asarray(wt(quant["layers"]["wq"], jnp.float32))
    ref = np.asarray(dense["layers"]["wq"])
    denom = np.maximum(np.max(np.abs(ref), axis=-2, keepdims=True), 1e-9)
    assert np.max(np.abs(back - ref) / denom) <= (0.5 / 127.0) * 1.05

    # Both load shapes serve: same greedy token path within quant tolerance.
    cache = KvCacheArrays.create(cfg, 16, dtype=jnp.float32)
    tables = jnp.tile(jnp.arange(1, 5, dtype=jnp.int32), (2, 1))
    toks = jnp.array([3, 7], jnp.int32)
    pos = jnp.array([20, 9], jnp.int32)
    act = jnp.ones((2,), bool)
    lg1, _, _ = llama.decode(dense, cfg, cache.k, cache.v, toks, pos, tables, act)
    lg2, _, _ = llama.decode(quant, qcfg, cache.k, cache.v, toks, pos, tables, act)
    cos = float(jnp.sum(lg1 * lg2) / (jnp.linalg.norm(lg1) * jnp.linalg.norm(lg2)))
    assert cos > 0.995


def test_int8_weights_shard_over_tp_mesh():
    """QuantW params must shard like their dense counterparts (q takes the
    weight's spec, the per-channel scale keeps only the output axis)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from dynamo_tpu.engine.sharding import ParallelConfig, build_mesh, shard_params

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qparams = quantize_params({**params, "layers": dict(params["layers"])})
    mesh = build_mesh(ParallelConfig(tp=8))
    sharded = shard_params(qparams, mesh, cfg.tie_word_embeddings)
    wq = sharded["layers"]["wq"]
    assert isinstance(wq, QuantW)
    assert wq.q.sharding.is_fully_addressable
    # outputs-axis sharded: per-device q shard is 1/8 of the columns
    assert wq.q.addressable_shards[0].data.shape[-1] * 8 == wq.q.shape[-1]
    assert wq.scale.addressable_shards[0].data.shape[-1] * 8 == wq.scale.shape[-1]
