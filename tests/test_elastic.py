"""Elastic prefill/decode tests (ROADMAP item 2): the per-worker capacity
dial (scheduler + mocker mirror + ``set_dial`` control op), token-boundary
request splits across workers (bit-identical to single-worker serving, KV
back to baseline on both sides, deadline folding), the planner's ratio
actuator (``decide_dial`` gates + fleet sweep), and the KV router's
dial-aware cost term. Ref: DynaServe arXiv:2504.09285 (continuous-ratio
pools); tests/test_disagg.py carries the non-split transfer coverage."""

import asyncio

import msgpack
import pytest

from dynamo_tpu.llm.kv_router import ActiveSequencesMultiWorker, KvScheduler
from dynamo_tpu.llm.kv_router.indexer import OverlapScores
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.metrics_aggregator import COUNTER_KEYS, GAUGE_KEYS
from dynamo_tpu.planner.controller import (
    DECODE,
    PREFILL,
    AutoscaleController,
    ControllerConfig,
    Decision,
    StaticCapacityModel,
)
from dynamo_tpu.planner.fleet import MockerFleet
from dynamo_tpu.planner.planner_core import ObservedLoad
from dynamo_tpu.runtime.distributed import DistributedRuntime
from tests.test_disagg import build_engine, collect, req, setup_disagg

ELASTIC_GAUGES = (
    "elastic_prefill_fraction",
    "elastic_prefill_budget",
    "elastic_decode_slots",
)
ELASTIC_COUNTERS = (
    "elastic_dial_changes_total",
    "degrade_disagg_to_colocated_total",
    "degrade_colocated_to_disagg_total",
    "split_prefills_total",
)


def load(rate, isl=100.0, osl=16.0):
    return ObservedLoad(request_rate=rate, avg_isl=isl, avg_osl=osl)


# --- scheduler dial (real engine) --------------------------------------------
async def test_scheduler_capacity_dial_identity_extremes_and_stats():
    """f=0.5 is the configured identity; f→1 doubles the mixed chunk budget
    (clamped to max_prefill_chunk) and shrinks decode slots to 1; f→0 pins
    the budget at one block while slots stay at the configured cap. The
    applied values ride the stats scrape."""
    engine = build_engine()
    sch = engine.scheduler
    base_budget = sch._base_mixed_prefill_budget
    base_slots = sch._base_max_running
    bs = sch.mc.block_size

    applied = engine.set_capacity_dial(0.5)
    assert applied == {
        "prefill_fraction": 0.5,
        "mixed_prefill_budget": min(base_budget, sch.sc.max_prefill_chunk),
        "decode_slots": base_slots,
    }

    applied = engine.set_capacity_dial(1.0)
    assert applied["mixed_prefill_budget"] == min(2 * base_budget, sch.sc.max_prefill_chunk)
    assert applied["decode_slots"] == 1
    assert sch.sc.max_running == 1

    applied = engine.set_capacity_dial(0.0)
    assert applied["mixed_prefill_budget"] == bs
    assert applied["decode_slots"] == base_slots

    # Out-of-range inputs clamp instead of wedging the worker.
    assert engine.set_capacity_dial(7.3)["prefill_fraction"] == 1.0
    assert engine.set_capacity_dial(-2.0)["prefill_fraction"] == 0.0

    stats = engine.stats_handler()
    assert stats["elastic_prefill_fraction"] == 0.0
    assert stats["elastic_prefill_budget"] == bs
    assert stats["elastic_decode_slots"] == base_slots
    assert stats["elastic_dial_changes_total"] == 5
    await engine.stop()


async def test_dial_shrink_then_restore_serves_correctly():
    """A live engine serves identically before, during, and after a dial
    swing — the shrunken decode-slot cap must not strand admitted work."""
    engine = build_engine()
    prompt = list(range(20, 52))
    ref, fin = await collect(engine, req(prompt))
    assert fin == "length" and len(ref) == 6

    engine.set_capacity_dial(1.0)  # decode slots → 1
    out, fin = await collect(engine, req(prompt))
    assert out == ref and fin == "length"

    engine.set_capacity_dial(0.5)  # back to the configured identity
    out, fin = await collect(engine, req(prompt))
    assert out == ref and fin == "length"
    assert engine.scheduler.allocator.num_active == 0
    await engine.stop()


# --- token-boundary splits ----------------------------------------------------
async def test_split_prefill_bit_identical_and_kv_baseline():
    """The elastic split contract: a request prefilled for its first
    ``split_at`` tokens on worker A and completed on worker B emits the
    exact token stream a single worker would, folds its deadline across the
    hop, and leaves BOTH allocators at baseline."""

    class _Capture:
        """Delegating engine shim so the test can see the decode-leg request
        exactly as the handler forwarded it."""

        def __init__(self, inner):
            self.inner = inner
            self.requests = []

        def generate(self, request, context):
            self.requests.append(request)
            return self.inner.generate(request, context)

        def stats_handler(self):
            return self.inner.stats_handler()

    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(drt)
        cap = _Capture(decode_engine)
        handler.engine = cap
        prompt = list(range(20, 68))  # 48 tokens, split after 2 blocks

        ref_engine = build_engine()
        ref, _ = await collect(ref_engine, req(prompt))
        await ref_engine.stop()

        r = req(prompt)
        r["disagg_params"] = {"split_at": 32}
        r["stop_conditions"]["deadline_ms"] = 60000.0
        out, fin = await collect(handler, r)

        assert out == ref, f"split-prefill stream {out} != single-worker {ref}"
        assert fin == "length"
        assert handler.remote_prefills == 1 and handler.split_prefills_total == 1

        # The decode leg carried the partial-injection marker and a folded
        # deadline: remaining budget, never the original (the hop already
        # spent wall clock) and never zero (max_tokens still governs).
        local_req = cap.requests[-1]
        assert local_req["_prefilled"]["prefill_len"] == 32
        folded = local_req["stop_conditions"]["deadline_ms"]
        assert 0.0 < folded < 60000.0
        assert local_req["stop_conditions"]["max_tokens"] == 6

        # KV baseline on both workers: the export was consumed on A, and
        # B's blocks free once the stream finishes.
        assert prefill_engine.scheduler.allocator.num_active == 0
        assert not prefill_engine.scheduler._pending_exports
        for _ in range(100):
            if decode_engine.scheduler.allocator.num_active == 0:
                break
            await asyncio.sleep(0.02)
        assert decode_engine.scheduler.allocator.num_active == 0

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


async def test_split_at_rejects_degenerate_boundaries():
    """split_at below one block or past the prompt is ignored (classic full
    handoff) — the knob can shape work, never corrupt it."""
    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(drt)
        prompt = list(range(20, 60))

        ref_engine = build_engine()
        ref, _ = await collect(ref_engine, req(prompt))
        await ref_engine.stop()

        for bad in (1, len(prompt), len(prompt) + 50):
            r = req(prompt)
            r["disagg_params"] = {"split_at": bad}
            out, fin = await collect(handler, r)
            assert out == ref and fin == "length"
        assert handler.split_prefills_total == 0
        assert handler.remote_prefills == 3
        assert prefill_engine.scheduler.allocator.num_active == 0

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


# --- proactive degradation ladder ---------------------------------------------
async def test_probe_degrades_both_directions_and_counts():
    """The load probe flips routing in BOTH directions before any wire hop:
    a saturated pool degrades disagg→co-located, a saturated local engine
    offloads co-located→disagg. Each flip lands on the paired counter and
    the stats scrape."""
    drt = await DistributedRuntime.detached()
    try:
        handler, prefill_engine, decode_engine, kvx, handle = await setup_disagg(drt)
        probe = {"prefill_saturated": True}
        handler.pool_load_probe = lambda: probe
        prompt = list(range(20, 60))

        out, fin = await collect(handler, req(prompt))
        assert fin == "length" and len(out) == 6
        assert handler.local_prefills == 1 and handler.remote_prefills == 0
        assert handler.degrade_disagg_to_colocated_total == 1

        # Reverse rung needs the length rule to say "local" first.
        from dynamo_tpu.llm.disagg import DisaggRouter, DisaggRouterConf

        handler.disagg_router = DisaggRouter(
            drt, "tiny", conf=DisaggRouterConf(max_local_prefill_length=100)
        )
        probe.clear()
        probe["local_saturated"] = True
        out, fin = await collect(handler, req(prompt))  # 40 < 100 ⇒ local, overridden
        assert fin == "length" and len(out) == 6
        assert handler.remote_prefills == 1
        assert handler.degrade_colocated_to_disagg_total == 1

        stats = handler.stats_handler()
        assert stats["degrade_disagg_to_colocated_total"] == 1
        assert stats["degrade_colocated_to_disagg_total"] == 1
        assert stats["split_prefills_total"] == 0

        await kvx.stop()
        await prefill_engine.stop()
        await decode_engine.stop()
    finally:
        await drt.shutdown()


# --- mocker mirror ------------------------------------------------------------
def test_mocker_dial_mirrors_scheduler_contract():
    m = MockTpuEngine(MockEngineArgs(max_batch=4, max_prefill_chunk=256))
    bs = m.args.block_size

    applied = m.set_capacity_dial(0.5)
    assert applied == {"prefill_fraction": 0.5, "mixed_prefill_budget": 256, "decode_slots": 4}

    assert m.set_capacity_dial(0.0)["mixed_prefill_budget"] == bs
    assert m.args.max_batch == 4
    assert m.set_capacity_dial(1.0)["decode_slots"] == 1
    assert m.set_capacity_dial(9.0)["prefill_fraction"] == 1.0
    assert m.elastic_dial_changes_total == 4

    m.note_degrade("disagg_to_colocated")
    m.note_degrade("colocated_to_disagg")
    with pytest.raises(ValueError, match="unknown degrade direction"):
        m.note_degrade("sideways")


def test_mocker_stats_families_match_engine_and_aggregator():
    """WIRE001 triangle: the mocker scrape carries the same elastic/degrade
    key families as the real engine scrape, and every one of them is
    registered in the aggregator's export tuples."""
    m = MockTpuEngine(MockEngineArgs())
    m.set_capacity_dial(0.75)
    m.note_degrade("disagg_to_colocated")
    stats = m.stats_handler()
    registered = set(GAUGE_KEYS) | set(COUNTER_KEYS)
    for key in ELASTIC_GAUGES + ELASTIC_COUNTERS:
        if key == "split_prefills_total":
            continue  # the disagg handler's counter, not a worker scrape key
        assert key in stats, f"mocker scrape missing {key}"
        assert key in registered, f"{key} not registered with the aggregator"
    assert "split_prefills_total" in registered
    assert stats["elastic_prefill_fraction"] == 0.75
    assert stats["degrade_disagg_to_colocated_total"] == 1

    # The dial gossips to routers on the metrics wire (ForwardPassMetrics).
    assert m.metrics().to_wire()["elastic_prefill_fraction"] == 0.75


def test_planner_keys_registered():
    for key in ("planner_elastic_ratio",):
        assert key in GAUGE_KEYS
    for key in ("planner_dial_total",):
        assert key in COUNTER_KEYS


# --- set_dial control op ------------------------------------------------------
async def test_set_dial_control_op_end_to_end():
    """The live-adjust path a planner actuator uses across processes:
    publish ``set_dial`` on the worker's control subject, the worker applies
    it to its engine and acks the applied values over reply_to."""
    drt = await DistributedRuntime.detached()
    try:
        engine = MockTpuEngine(MockEngineArgs(max_batch=4, max_prefill_chunk=256))
        ep = drt.namespace("elasticctl").component("w").endpoint("gen")
        handle = await ep.serve_endpoint(engine, stats_handler=engine.stats_handler)

        reply_subject = "elastic_test.dial_ack"
        sub = await drt.bus.subscribe(reply_subject)
        await drt.bus.publish(
            handle.instance.control_subject,
            msgpack.packb({"op": "set_dial", "prefill_fraction": 0.9}, use_bin_type=True),
            reply_to=reply_subject,
        )
        msg = await sub.next(timeout=5.0)
        assert msg is not None, "set_dial never acked"
        applied = msgpack.unpackb(msg.data, raw=False)
        assert applied["prefill_fraction"] == 0.9
        assert applied["decode_slots"] == 1
        assert engine._elastic_fraction == 0.9
        assert engine.stats_handler()["elastic_dial_changes_total"] == 1
        await sub.unsubscribe()
    finally:
        await drt.shutdown()


# --- planner ratio actuator ---------------------------------------------------
def test_decide_dial_tracks_isl_osl_mix():
    c = AutoscaleController(
        ControllerConfig(dial_deadband=0.05, dial_min_interval_s=30.0),
        StaticCapacityModel(400.0, 80.0, utilization=1.0),
    )
    # Prefill-heavy mix: pre = 400/400 = 1.0s, dec = 16/80 = 0.2s.
    d = c.decide_dial(load(4.0, isl=400.0, osl=16.0), now=0.0)
    assert d is not None and d.action == "dial" and d.pool == "fleet"
    assert d.fraction == pytest.approx(1.0 / 1.2)
    assert d.count == 0  # a dial is not a scale event

    # Idle fleet holds the dial.
    assert c.decide_dial(load(0.0), now=40.0) is None

    # Deadband: the same mix again is a no-op, whatever the clock says.
    assert c.decide_dial(load(4.0, isl=400.0, osl=16.0), now=100.0) is None

    # Min interval: a genuinely new mix still waits out the chatter guard.
    decode_heavy = load(4.0, isl=100.0, osl=100.0)
    assert c.decide_dial(decode_heavy, now=10.0) is None
    d2 = c.decide_dial(decode_heavy, now=40.0)
    assert d2 is not None
    assert d2.fraction == pytest.approx(0.25 / 1.5)

    stats = c.to_stats()
    assert stats["planner_dial_total"] == 2
    assert stats["planner_elastic_ratio"] == pytest.approx(0.25 / 1.5)


async def test_fleet_apply_sweeps_dial_to_all_workers():
    drt = await DistributedRuntime.detached()
    try:
        fleet = MockerFleet(
            drt, "elasticfleet",
            make_args=lambda component: MockEngineArgs(speedup_ratio=50.0),
            publish_kv_events=False,
        )
        await fleet.add_worker(PREFILL)
        await fleet.add_worker(DECODE)
        await fleet.apply([Decision("fleet", "dial", 0, 0, 0, fraction=0.8)])
        for pool in (PREFILL, DECODE):
            for w in fleet.pools[pool]:
                assert w.engine._elastic_fraction == 0.8
                assert w.engine.elastic_dial_changes_total == 1
    finally:
        await drt.shutdown()


# --- KV-router dial-aware cost ------------------------------------------------
def test_router_cost_identity_at_half_dial():
    """f = 0.5 on every worker reproduces the pre-elastic cost exactly —
    the dial term is invisible until someone actually moves a dial."""
    seqs = ActiveSequencesMultiWorker(block_size=16)
    sched = KvScheduler(seqs)
    base = sched.select_worker([1], prompt_blocks=6, overlaps=OverlapScores(scores={1: 2}))
    dialed = sched.select_worker(
        [1], prompt_blocks=6, overlaps=OverlapScores(scores={1: 2}),
        prefill_fractions={1: 0.5},
    )
    assert dialed.cost == base.cost


def test_router_prefers_prefill_dialed_worker_for_prefill_heavy_work():
    seqs = ActiveSequencesMultiWorker(block_size=16)
    sched = KvScheduler(seqs)
    # Identical workers, identical (zero) overlap: the one dialed toward
    # prefill clears the prompt's blocks faster, so it must win.
    d = sched.select_worker(
        [1, 2], prompt_blocks=8, overlaps=OverlapScores(),
        prefill_fractions={1: 0.9, 2: 0.1},
    )
    assert d.worker == 1

    # Decode cost is dial-independent: with no prefill work left the
    # fractions cannot tip the choice toward a loaded worker.
    for i in range(10):
        seqs.add_request(f"r{i}", 1, prompt_tokens=64, overlap_blocks=0)
        seqs.mark_prefill_done(f"r{i}")
    d = sched.select_worker(
        [1, 2], prompt_blocks=0, overlaps=OverlapScores(),
        prefill_fractions={1: 0.9, 2: 0.1},
    )
    assert d.worker == 2
