"""/v1/embeddings + /v1/responses endpoint tests (ref: openai.rs:369,:714)."""

import aiohttp
import numpy as np

from dynamo_tpu.engine.embeddings import EmbeddingEngine
from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.discovery import ModelManager
from dynamo_tpu.llm.entrypoint import build_embeddings_pipeline, build_local_pipeline
from dynamo_tpu.llm.http.service import HttpService
from dynamo_tpu.llm.tokenizer import ByteTokenizer

import jax
import jax.numpy as jnp

MODEL = "tiny-embed"


async def make_service():
    cfg = get_config("tiny")
    engine = TpuEngine.build(
        EngineArgs(
            model="tiny",
            dtype="float32",
            scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4]),
        )
    )
    tok = ByteTokenizer()
    manager = ModelManager()
    manager.add_model("chat", MODEL, build_local_pipeline(tok, engine))
    manager.add_model(
        "embeddings",
        MODEL,
        build_embeddings_pipeline(tok, EmbeddingEngine(cfg, engine.scheduler.params, buckets=[16, 32, 64])),
    )
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, engine


def test_embed_fn_deterministic_and_normalized():
    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    ids = jnp.asarray(list(range(10, 26)), dtype=jnp.int32)
    v1 = llama.embed(params, cfg, ids, jnp.int32(12))
    v2 = llama.embed(params, cfg, ids, jnp.int32(12))
    assert v1.shape == (cfg.hidden_size,)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v2))
    assert abs(float(jnp.linalg.norm(v1)) - 1.0) < 1e-5
    # Padding beyond valid_len must not change the embedding.
    ids_padded = jnp.concatenate([ids[:12], jnp.full((20,), 99, dtype=jnp.int32)])
    v3 = llama.embed(params, cfg, ids_padded, jnp.int32(12))
    np.testing.assert_allclose(np.asarray(v1), np.asarray(v3), rtol=1e-5, atol=1e-5)


async def test_embeddings_endpoint():
    service, engine = await make_service()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/embeddings"
            body = {"model": MODEL, "input": ["hello world", "goodbye"]}
            async with s.post(url, json=body) as r:
                assert r.status == 200
                data = await r.json()
                assert data["object"] == "list" and len(data["data"]) == 2
                assert len(data["data"][0]["embedding"]) == 64  # tiny hidden
                assert data["usage"]["prompt_tokens"] > 0
            # Unknown model → 404.
            async with s.post(url, json={"model": "nope", "input": "x"}) as r:
                assert r.status == 404
            # Bad input → 400.
            async with s.post(url, json={"model": MODEL, "input": []}) as r:
                assert r.status == 400
    finally:
        await service.stop()
        await engine.stop()


async def test_responses_endpoint():
    service, engine = await make_service()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/responses"
            body = {
                "model": MODEL,
                "input": "say hi",
                "instructions": "be terse",
                "max_output_tokens": 5,
            }
            async with s.post(url, json=body) as r:
                assert r.status == 200
                data = await r.json()
                assert data["object"] == "response" and data["status"] == "completed"
                msg = data["output"][0]
                assert msg["role"] == "assistant"
                assert msg["content"][0]["type"] == "output_text"
                assert data["usage"]["output_tokens"] == 5
            async with s.post(url, json={"model": MODEL}) as r:
                assert r.status == 400
    finally:
        await service.stop()
        await engine.stop()


async def test_responses_rejects_bad_items():
    service, engine = await make_service()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/responses"
            # Malformed input item → structured 400, not a 500 crash.
            async with s.post(url, json={"model": MODEL, "input": [42]}) as r:
                assert r.status == 400
                assert "error" in await r.json()
    finally:
        await service.stop()
        await engine.stop()


async def test_responses_streaming_contract():
    """Semantic SSE event sequence (ref: openai.rs:714): created →
    output_item.added → content_part.added → output_text.delta* →
    output_text.done → content_part.done → output_item.done → completed;
    deltas reassemble to the final text; sequence numbers monotone."""
    import json as _json

    service, engine = await make_service()
    try:
        async with aiohttp.ClientSession() as s:
            url = f"http://127.0.0.1:{service.port}/v1/responses"
            body = {"model": MODEL, "input": "stream me", "max_output_tokens": 5, "stream": True}
            events = []
            async with s.post(url, json=body) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/event-stream")
                async for line in r.content:
                    if line.startswith(b"data:"):
                        events.append(_json.loads(line[5:]))
        types = [e["type"] for e in events]
        assert types[0] == "response.created"
        for required in (
            "response.output_item.added", "response.content_part.added",
            "response.output_text.delta", "response.output_text.done",
            "response.content_part.done", "response.output_item.done",
            "response.completed",
        ):
            assert required in types, f"missing {required} in {types}"
        assert [e["sequence_number"] for e in events] == list(range(len(events)))
        deltas = "".join(e["delta"] for e in events if e["type"] == "response.output_text.delta")
        done = next(e for e in events if e["type"] == "response.output_text.done")
        assert deltas == done["text"] and deltas
        completed = next(e for e in events if e["type"] == "response.completed")
        assert completed["response"]["status"] == "completed"
        assert completed["response"]["usage"]["output_tokens"] == 5
        assert completed["response"]["output"][0]["content"][0]["text"] == deltas
    finally:
        await service.stop()
        await engine.stop()


async def test_responses_tools_mapping():
    """Responses tool defs map to chat shape; tool_calls come back as
    function_call output items (unary + streamed)."""
    from dynamo_tpu.llm.protocols import openai as oai

    chat_tools = oai.responses_tools_to_chat(
        [{"type": "function", "name": "get_weather", "parameters": {"type": "object"}}]
    )
    assert chat_tools == [
        {"type": "function", "function": {"name": "get_weather", "parameters": {"type": "object"}}}
    ]
    item = oai.responses_function_call_item(
        "r1", 0, {"id": "call_9", "function": {"name": "get_weather", "arguments": '{"city":"SF"}'}}
    )
    assert item["type"] == "function_call"
    assert item["call_id"] == "call_9"
    assert item["name"] == "get_weather"
    assert item["arguments"] == '{"city":"SF"}'
    calls = [{"id": "call_9", "function": {"name": "f", "arguments": "{}"}}]
    resp = oai.responses_response("r1", "m", "ok", {"prompt_tokens": 1, "completion_tokens": 2},
                                  tool_calls=calls)
    assert [o["type"] for o in resp["output"]] == ["message", "function_call"]
    # Tool-call-only responses omit the empty message item.
    resp = oai.responses_response("r1", "m", "", {}, tool_calls=calls)
    assert [o["type"] for o in resp["output"]] == ["function_call"]
