"""Ring attention vs dense causal attention on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.attention.ring import ring_attention
from dynamo_tpu.engine.sharding import ParallelConfig, build_mesh


def dense_causal(q, k, v):
    T, H, hd = q.shape
    KVH = k.shape[1]
    G = H // KVH
    qg = q.reshape(T, KVH, G, hd)
    scores = jnp.einsum("tkgd,skd->ktgs", qg, k).astype(jnp.float32) * hd**-0.5
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    scores = jnp.where(mask[None, :, None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("ktgs,skd->ktgd", p.astype(v.dtype), v)
    return out.transpose(1, 0, 2, 3).reshape(T, H, hd)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(sp):
    mesh = build_mesh(ParallelConfig(sp=sp))
    T, H, KVH, hd = 64, 4, 2, 16
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (T, H, hd), dtype=jnp.float32)
    k = jax.random.normal(kk, (T, KVH, hd), dtype=jnp.float32)
    v = jax.random.normal(kv, (T, KVH, hd), dtype=jnp.float32)

    ref = dense_causal(q, k, v)
    out = ring_attention(q, k, v, mesh, axis_name="sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_non_causal():
    mesh = build_mesh(ParallelConfig(sp=4))
    T, H, KVH, hd = 32, 2, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(1), (T, H, hd), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(2), (T, KVH, hd), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(3), (T, KVH, hd), dtype=jnp.float32)

    # Non-causal reference: plain softmax attention.
    qg = q.reshape(T, KVH, H // KVH, hd)
    scores = jnp.einsum("tkgd,skd->ktgs", qg, k).astype(jnp.float32) * hd**-0.5
    p = jax.nn.softmax(scores, axis=-1)
    ref = jnp.einsum("ktgs,skd->ktgd", p, v).transpose(1, 0, 2, 3).reshape(T, H, hd)

    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_under_jit_compiles_once():
    mesh = build_mesh(ParallelConfig(sp=2))
    T, H, KVH, hd = 32, 2, 1, 8
    q = jax.random.normal(jax.random.PRNGKey(4), (T, H, hd), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(5), (T, KVH, hd), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(6), (T, KVH, hd), dtype=jnp.float32)
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh))
    out = fn(q, k, v)
    ref = dense_causal(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
