"""Chaos plane scenario suite: deterministic fault injection through the
real demo stack (wire-path mocker workers + PushRouter + Migration), with
the failure lifecycle it exposes — deadlines, retry budgets, circuit
breaker, drain, cancellation — asserted end to end.

Every scenario pins zero token loss/duplication on surviving requests
(mocker ``token_rule="position"``: token = sequence position, so a migrated
continuation is bit-identical to an uninterrupted run), bounded recovery,
and KV-allocator counters back at baseline after the failure. The injector
is seeded and pass-counted, so two runs of the same scenario produce
identical injection logs (asserted in test_injection_determinism).
"""

import asyncio
import glob
import json
import time

import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.entrypoint import RouterEngine
from dynamo_tpu.llm.migration import Migration, _MigrationEngine
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context, StreamDisconnect
from dynamo_tpu.runtime.push_router import (
    CLOSED,
    OPEN,
    CircuitBreaker,
    NoInstancesError,
    PushRouter,
    RetryPolicy,
)


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process-global injector clean."""
    yield
    faults.disarm()


def req(tokens, max_tokens=8, deadline_ms=None):
    stop = {"max_tokens": max_tokens}
    if deadline_ms is not None:
        stop["deadline_ms"] = deadline_ms
    return {"token_ids": list(tokens), "sampling_options": {}, "stop_conditions": stop}


async def spawn_worker(drt, ep, lease_ttl_s=None, **mock_kw):
    """One wire-path mocker worker (local fast path disabled, like a real
    multi-process deployment)."""
    kw = dict(speedup_ratio=50.0, num_blocks=128, token_rule="position")
    kw.update(mock_kw)
    engine = MockTpuEngine(MockEngineArgs(**kw))
    handle = await ep.serve_endpoint(
        engine.generate, stats_handler=engine.stats_handler, lease_ttl_s=lease_ttl_s
    )
    drt.local_engines.pop(handle.instance.instance_id)
    return engine, handle


async def chaos_stack(drt, ns, n_workers=2, *, migration_limit=2, on_migrate=None,
                      retry=None, breaker=None, **mock_kw):
    """Demo stack: N wire-path mockers behind PushRouter + Migration."""
    ep = drt.namespace(ns).component("w").endpoint("gen")
    workers = [await spawn_worker(drt, ep, **mock_kw) for _ in range(n_workers)]
    client = await ep.client()
    await client.wait_for_instances(n_workers, timeout=5)
    router = PushRouter(
        client,
        retry=retry or RetryPolicy(max_retries=2, backoff_base_s=0.01, seed=0),
        breaker=breaker,
    )
    engine = Migration(migration_limit, on_migrate=on_migrate).attach(RouterEngine(router))
    return ep, client, router, engine, workers


async def collect(engine, request, ctx=None):
    got, finish = [], None
    async for item in engine.generate(dict(request), ctx or Context()):
        data = item.data if hasattr(item, "data") else item
        if isinstance(data, dict):
            got.extend(data.get("token_ids") or [])
            if data.get("finish_reason"):
                finish = data["finish_reason"]
    return got, finish


def assert_drained(workers):
    """KV baseline: every allocator back to zero active blocks."""
    for engine, _ in workers:
        assert engine.allocator.num_active == 0, (
            f"allocator leaked {engine.allocator.num_active} active blocks"
        )


# --- scenario 1: worker crash mid-stream --------------------------------------
async def test_crash_migrates_with_zero_token_loss():
    """Engine death after N steps: the stream drops abruptly, Migration
    replays on the survivor, and the client sees the exact uninterrupted
    token sequence — nothing lost, nothing duplicated."""
    drt = await DistributedRuntime.detached()
    migrations = []
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaos1", on_migrate=lambda: migrations.append(1))
        faults.arm(faults.FaultInjector(
            [{"site": "worker.step", "kind": "crash", "after": 4}], seed=7))

        t0 = time.monotonic()
        got, finish = await collect(engine, req(range(10), max_tokens=8))
        elapsed = time.monotonic() - t0

        # Position tokens: an uninterrupted run yields exactly 10..17.
        assert got == list(range(10, 18)), got
        assert finish == "length"
        assert len(migrations) == 1
        inj = faults.get_injector()
        assert [(r["site"], r["kind"]) for r in inj.log] == [("worker.step", "crash")]
        assert inj.to_stats()["faults_crash_total"] == 1
        assert elapsed < 5.0, f"recovery took {elapsed:.1f}s"
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 2: stream drop after K tokens -----------------------------------
async def test_stream_drop_after_k_tokens_migrates():
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(drt, "chaos2")
        faults.arm(faults.FaultInjector(
            [{"site": "worker.frame", "kind": "stream_drop", "after": 3}], seed=7))

        got, finish = await collect(engine, req(range(10), max_tokens=8))
        assert got == list(range(10, 18)), got
        assert finish == "length"
        log = faults.get_injector().log
        assert [(r["site"], r["kind"]) for r in log] == [("worker.frame", "stream_drop")]
        # The drop fired on the 4th frame: exactly 3 frames reached the wire.
        assert log[0]["attrs"]["frame"] == "4"
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 3: worker hang → deadline eviction ------------------------------
async def test_hang_hits_deadline_and_frees_kv():
    """A wedged engine loop cannot hold the request past its deadline: the
    mocker's sweep evicts with finish_reason 'timeout' and the allocator
    returns to baseline."""
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaos3", n_workers=1, migration_limit=0, speedup_ratio=1.0,
            itl_base_ms=5.0)
        faults.arm(faults.FaultInjector(
            [{"site": "worker.step", "kind": "hang", "after": 3, "delay_s": 0.6}],
            seed=7))

        t0 = time.monotonic()
        got, finish = await collect(engine, req(range(10), max_tokens=100, deadline_ms=250))
        elapsed = time.monotonic() - t0

        assert finish == "timeout"
        assert 0 < len(got) < 100  # some tokens streamed before the wedge
        assert elapsed < 3.0, f"recovery took {elapsed:.1f}s"
        mocker = workers[0][0]
        assert mocker.timeouts_total == 1
        assert mocker.stats_handler()["request_timeouts_total"] == 1
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 4: lease loss → eviction → migration lands on survivor ----------
async def test_lease_loss_evicts_instance_and_migrates_once():
    """Kill a worker mid-stream (blocked lease renewal + engine crash): the
    router prunes the instance when the lease lapses — before the next
    route — the Migration operator lands the replay on the survivor, and
    migrations_total increments exactly once."""
    drt = await DistributedRuntime.detached()
    migrations = []
    try:
        ep = drt.namespace("chaos4").component("w").endpoint("gen")
        # Victim with a short lease: expiry lands mid-stream (~0.5-1s; the
        # stream runs ~40ms/token * 60 tokens = 2.4s sim).
        victim, h_victim = await spawn_worker(
            drt, ep, speedup_ratio=1.0, itl_base_ms=40.0, lease_ttl_s=0.5)
        vid = h_victim.instance.instance_id
        # Block the victim's lease renewals from now on (before its first
        # ttl/3 keepalive fires).
        faults.arm(faults.FaultInjector([
            {"site": "lease.keepalive", "kind": "lease_drop", "count": 0,
             "match": {"lease": f"{vid:x}"}},
        ], seed=7))
        survivor, h_surv = await spawn_worker(
            drt, ep, speedup_ratio=1.0, itl_base_ms=40.0)
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = PushRouter(client, retry=RetryPolicy(max_retries=2, backoff_base_s=0.01, seed=0))
        engine = Migration(2, on_migrate=lambda: migrations.append(1)).attach(RouterEngine(router))
        # Deterministically land the first route on the victim (lease ids
        # are random, so pin round-robin's starting point).
        router._rr = sorted(client.instances).index(vid)

        stream_task = asyncio.create_task(collect(engine, req(range(10), max_tokens=60)))

        # The router must evict the victim BEFORE the next route.
        deadline = time.monotonic() + 5.0
        while vid in client.instances and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert vid not in client.instances, "lease expiry did not evict the instance"
        assert stream_task.done() is False, "stream should still be mid-flight"

        # Now the 'process' dies: every live stream drops abruptly.
        victim._crash_all()

        got, finish = await stream_task
        assert got == list(range(10, 70)), "migrated stream lost or duplicated tokens"
        assert finish == "length"
        assert len(migrations) == 1, f"expected exactly one migration, got {len(migrations)}"
        # The replay landed on the survivor (only live instance).
        assert router.decisions[-1]["instance"] == f"{h_surv.instance.instance_id:x}"
        assert survivor.allocator.num_active == 0
        assert any(r["kind"] == "lease_drop" for r in faults.get_injector().log)
    finally:
        await drt.shutdown()


# --- scenario 5: control-plane delay ------------------------------------------
async def test_control_plane_delay_still_completes():
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(drt, "chaos5")
        faults.arm(faults.FaultInjector([
            {"site": "bus.publish", "kind": "delay", "count": 0, "delay_s": 0.1,
             "match": {"subject_prefix": "rq."}},
        ], seed=7))

        t0 = time.monotonic()
        got, finish = await collect(engine, req(range(10), max_tokens=8))
        elapsed = time.monotonic() - t0
        assert got == list(range(10, 18))
        assert finish == "length"
        assert elapsed >= 0.1  # the injected hop delay is real
        assert elapsed < 3.0
        assert faults.get_injector().to_stats()["faults_delay_total"] >= 1
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 6: control-plane partition + client-side deadline backstop ------
async def test_partition_blackholes_push_then_recovers():
    """The first request push is dropped on the floor (partition): nothing
    ever reaches a worker, the deadline backstop cancels the wait, no KV is
    held anywhere — and the next request sails through (count=1)."""
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(drt, "chaos6", migration_limit=0)
        faults.arm(faults.FaultInjector([
            {"site": "bus.publish", "kind": "partition", "count": 1,
             "match": {"subject_prefix": "rq."}},
        ], seed=7))

        ctx = Context()
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(collect(engine, req(range(10)), ctx), timeout=0.5)
        ctx.stop_generating()
        await asyncio.sleep(0.05)
        assert_drained(workers)  # the blackholed request held no blocks

        got, finish = await collect(engine, req(range(10), max_tokens=8))
        assert got == list(range(10, 18))
        assert finish == "length"
        assert faults.get_injector().to_stats()["faults_partition_total"] == 1
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 7: slow worker degradation --------------------------------------
async def test_slow_worker_degrades_but_completes():
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaos7", n_workers=1, speedup_ratio=50.0)
        faults.arm(faults.FaultInjector([
            {"site": "worker.step", "kind": "slow", "count": 0, "factor": 5.0},
        ], seed=7))

        got, finish = await collect(engine, req(range(10), max_tokens=8))
        assert got == list(range(10, 18))
        assert finish == "length"
        mocker = workers[0][0]
        # The stretched step time is visible to telemetry (ITL digests feed
        # the anomaly detector in production).
        assert mocker.last_step_ms >= 5.0 * 3.0  # ≥ factor × itl_base floor
        assert faults.get_injector().to_stats()["faults_slow_total"] >= 8
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- scenario 8: stats-scrape blackout → fleet worker_lost incident -----------
async def test_stats_blackout_fires_worker_lost_with_router_evidence(tmp_path):
    """A worker that stops answering scrapes vanishes from the aggregator's
    view: the fleet incident plane fires worker_lost and the bundle carries
    the router's routing-decision ring as evidence."""
    from dynamo_tpu.metrics_aggregator import MetricsAggregator

    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(drt, "chaos8")
        # A routed request so the router's evidence ring has decisions.
        got, _ = await collect(engine, req(range(10), max_tokens=4))
        assert got

        agg = MetricsAggregator(drt, "chaos8", "w", "gen",
                                incident_dir=str(tmp_path / "incidents"))
        agg.client = client
        stats = await client.scrape_stats(timeout=0.5)
        assert len(stats) == 2
        agg.export_stats(stats)

        blackout_id = workers[0][1].instance.instance_id
        faults.arm(faults.FaultInjector([
            {"site": "stats.reply", "kind": "stats_blackout", "count": 0,
             "match": {"instance": f"{blackout_id:x}"}},
        ], seed=7))
        stats = await client.scrape_stats(timeout=0.5)
        assert len(stats) == 1  # the blackout worker never replied
        agg.export_stats(stats)

        plane = agg.incidents.to_stats()
        assert plane["incidents_worker_lost_total"] == 1
        bundles = glob.glob(str(tmp_path / "incidents" / "incident_*worker_lost*.json"))
        assert len(bundles) == 1
        bundle = json.load(open(bundles[0]))
        evidence = bundle["evidence"]
        router_ev = next(v for k, v in evidence.items() if k.startswith("router:"))
        assert router_ev["recent_decisions"], "bundle must carry routing decisions"
        assert bundle["detector"]["last_values"]["worker_lost"] == 1.0
    finally:
        await drt.shutdown()


# --- determinism: fixed seed ⇒ identical injection sequences ------------------
async def test_injection_determinism_fixed_seed():
    """Two runs of the same seeded scenario against the same workload
    produce byte-identical injection logs (site, kind, pass, attrs)."""

    async def run_once():
        drt = await DistributedRuntime.detached()
        try:
            _, client, router, engine, workers = await chaos_stack(drt, "chaosd")
            inj = faults.arm(faults.FaultInjector([
                {"site": "worker.frame", "kind": "stream_drop", "after": 2},
                {"site": "worker.frame", "kind": "slow", "after": 5, "count": 2,
                 "delay_s": 0.0, "probability": 0.5},
            ], seed=123))
            got, finish = await collect(engine, req(range(10), max_tokens=8))
            assert got == list(range(10, 18))
            # Strip the timing-free identity of each injection.
            return [(r["n"], r["site"], r["kind"], r["pass"], r["attrs"].get("frame"))
                    for r in inj.log]
        finally:
            faults.disarm()
            await drt.shutdown()

    log1 = await run_once()
    log2 = await run_once()
    assert log1 == log2, f"injection sequences diverged:\n{log1}\n{log2}"
    assert log1, "scenario must inject at least once"


# --- retry budget + circuit breaker -------------------------------------------
async def test_retry_budget_waits_out_rolling_restart():
    """Zero instances at route time: the retry budget's backoff outlives a
    short instance gap, and the request lands once a worker registers."""
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("chaosr").component("w").endpoint("gen")
        client = await ep.client()
        router = PushRouter(client, retry=RetryPolicy(max_retries=5, backoff_base_s=0.05, seed=1))
        engine = Migration(0).attach(RouterEngine(router))

        async def late_spawn():
            await asyncio.sleep(0.1)
            return await spawn_worker(drt, ep)

        spawn_task = asyncio.create_task(late_spawn())
        got, finish = await collect(engine, req(range(10), max_tokens=4))
        await spawn_task
        assert got == list(range(10, 14))
        assert router.retries_total >= 1
    finally:
        await drt.shutdown()


async def test_retry_budget_exhausts_to_no_instances():
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("chaosr2").component("w").endpoint("gen")
        client = await ep.client()
        router = PushRouter(client, retry=RetryPolicy(max_retries=2, backoff_base_s=0.005, seed=1))
        with pytest.raises(NoInstancesError):
            async for _ in router.generate(req(range(4)), Context()):
                pass
        assert router.retries_total == 2
    finally:
        await drt.shutdown()


def test_circuit_breaker_state_machine():
    t = [0.0]
    cb = CircuitBreaker(threshold=3, cooldown_s=5.0, clock=lambda: t[0])
    for _ in range(2):
        cb.record_failure(7)
    assert cb.state_of(7) == CLOSED and cb.blocked_instances() == set()
    cb.record_failure(7)  # third consecutive → trip
    assert cb.state_of(7) == OPEN
    assert cb.blocked_instances() == {7}
    t[0] = 4.9
    assert cb.blocked_instances() == {7}  # cooldown not elapsed
    t[0] = 5.1
    assert cb.blocked_instances() == set()  # half-open: probe allowed
    cb.note_dispatch(7)  # probe in flight
    assert cb.blocked_instances() == {7}  # no second probe
    cb.record_failure(7)  # probe failed → re-open, fresh cooldown
    assert cb.state_of(7) == OPEN
    t[0] = 10.3
    assert cb.blocked_instances() == set()
    cb.note_dispatch(7)
    cb.record_success(7)  # probe succeeded → closed
    assert cb.state_of(7) == CLOSED
    assert cb.snapshot()["trips_total"] == 2
    assert cb.snapshot()["workers"]["7"]["failures"] == 0


async def test_breaker_trips_and_routes_around_flaky_worker():
    """A worker whose streams keep dying trips its circuit after threshold
    consecutive failures; subsequent requests route straight to the healthy
    worker without paying the failure first."""
    drt = await DistributedRuntime.detached()
    try:
        breaker = CircuitBreaker(threshold=2, cooldown_s=30.0)
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaosb", migration_limit=3, breaker=breaker)
        flaky_id = workers[0][1].instance.instance_id
        healthy_id = workers[1][1].instance.instance_id
        faults.arm(faults.FaultInjector([
            {"site": "worker.frame", "kind": "stream_drop", "count": 0, "every": 1,
             "match": {"instance": f"{flaky_id:x}"}},
        ], seed=7))

        # Two requests: each first routes to the flaky worker (round-robin
        # pinned — lease ids are random), fails, and migrates to the healthy
        # one. Two failures trip the circuit.
        flaky_idx = sorted(client.instances).index(flaky_id)
        for _ in range(2):
            router._rr = flaky_idx
            got, finish = await collect(engine, req(range(10), max_tokens=4))
            assert got == list(range(10, 14))
        assert breaker.state_of(flaky_id) == OPEN

        # With the circuit open, routes skip the flaky worker entirely: the
        # injector's per-instance spec sees no more passes.
        drops_before = faults.get_injector().to_stats()["faults_stream_drop_total"]
        for _ in range(3):
            got, _ = await collect(engine, req(range(10), max_tokens=4))
            assert got == list(range(10, 14))
            assert router.decisions[-1]["instance"] == f"{healthy_id:x}"
        assert faults.get_injector().to_stats()["faults_stream_drop_total"] == drops_before
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- drain lifecycle ----------------------------------------------------------
async def test_drain_finishes_in_flight_and_deregisters():
    """POST /drain semantics (ServeHandle.stop drain path): deregister so
    routers stop sending, finish the in-flight stream, count the drain."""
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaosdr", speedup_ratio=1.0, itl_base_ms=20.0)
        mocker_a, handle_a = workers[0]
        aid = handle_a.instance.instance_id

        stream_task = asyncio.create_task(collect(engine, req(range(10), max_tokens=20)))
        await asyncio.sleep(0.1)
        assert not stream_task.done()

        # Scrape mid-stream: the draining gauge flips once the drain starts.
        drain_task = asyncio.create_task(handle_a.stop(drain=True))
        await asyncio.sleep(0.05)
        stats = await client.scrape_stats(timeout=0.5)
        if aid in stats:  # stats loop alive during the drain window
            assert stats[aid]["draining"] == 1.0

        got, finish = await stream_task
        await drain_task
        assert got == list(range(10, 30)), "drain must not lose in-flight tokens"
        assert finish == "length"
        assert handle_a._ingress.drains_total == 1
        assert aid not in client.instances
        assert mocker_a.allocator.num_active == 0

        # The drained worker is gone from routing: new work lands elsewhere.
        got, _ = await collect(engine, req(range(10), max_tokens=4))
        assert got == list(range(10, 14))
        assert router.decisions[-1]["instance"] == f"{workers[1][1].instance.instance_id:x}"
    finally:
        await drt.shutdown()


async def test_drain_timeout_migrates_in_flight_work():
    """A drain that cannot finish within shutdown_timeout_s severs the
    remaining streams — which migrates them: the client still sees the
    complete, uninterrupted token sequence."""
    drt = await DistributedRuntime.detached()
    try:
        drt.runtime.config.runtime.shutdown_timeout_s = 0.2
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaosdm", speedup_ratio=1.0, itl_base_ms=30.0)
        mocker_a, handle_a = workers[0]

        stream_task = asyncio.create_task(collect(engine, req(range(10), max_tokens=40)))
        await asyncio.sleep(0.15)
        assert not stream_task.done()
        await handle_a.stop(drain=True)  # 0.2s budget ≪ ~1.2s of stream left

        got, finish = await stream_task
        assert got == list(range(10, 50)), "severed stream must migrate losslessly"
        assert finish == "length"
        assert workers[1][0].allocator.num_active == 0
    finally:
        await drt.shutdown()


async def test_draining_worker_rejects_new_pushes_to_migration():
    """A request that races the drain window (stale route) is answered with
    a disconnect error, and Migration replays it on a live worker."""
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("chaosdj").component("w").endpoint("gen")
        mock_a, handle_a = await spawn_worker(drt, ep)
        mock_b, handle_b = await spawn_worker(drt, ep)
        # A STALE client: still believes both instances exist (the race).
        stale = await ep.client()
        await stale.wait_for_instances(2, timeout=5)
        handle_a._ingress.begin_drain()  # drain begun; key deletion pending

        router = PushRouter(stale, retry=RetryPolicy(seed=0))
        engine = Migration(2).attach(RouterEngine(router))
        got, finish = await collect(engine, req(range(10), max_tokens=6))
        assert got == list(range(10, 16))
        assert finish == "length"
        assert router.decisions[-1]["instance"] == f"{handle_b.instance.instance_id:x}"
    finally:
        await drt.shutdown()


# --- cancellation propagation -------------------------------------------------
async def test_cancellation_mid_stream_frees_kv_blocks():
    """Client stop mid-stream → prompt cancel over the control subject →
    mocker reaps the sequence → allocator back to baseline (prefix-cache
    refcounts released)."""
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaosc", n_workers=1, speedup_ratio=1.0, itl_base_ms=20.0)
        mocker = workers[0][0]
        ctx = Context()
        got = []
        async for item in engine.generate(req(range(64), max_tokens=100), ctx):
            data = item.data if hasattr(item, "data") else item
            if isinstance(data, dict) and data.get("token_ids"):
                got.extend(data["token_ids"])
                if len(got) >= 2:
                    ctx.stop_generating()
            if isinstance(data, dict) and data.get("finish_reason"):
                assert data["finish_reason"] == "cancelled"
                break

        deadline = time.monotonic() + 3.0
        while mocker.allocator.num_active and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert mocker.allocator.num_active == 0, "cancelled request leaked KV blocks"
        assert not mocker.running and not mocker.waiting
        assert 2 <= len(got) < 100
    finally:
        await drt.shutdown()


# --- deadline eviction on the REAL scheduler ----------------------------------
def test_scheduler_deadline_evicts_and_frees_blocks():
    """Real TpuEngine scheduler: a past-deadline row (waiting or running) is
    evicted with finish_reason 'timeout', its KV freed, while batchmates
    finish untouched."""
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.config import get_config
    from dynamo_tpu.engine.models import llama
    from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

    cfg = get_config("tiny")
    params = llama.init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    sched = Scheduler(cfg, params, SchedulerConfig(
        num_blocks=64, prefill_buckets=[16, 32, 64], decode_buckets=[1, 2, 4],
        enable_prefix_caching=False,
    ), dtype=jnp.float32)

    # r0: normal. r1: deadline already lapsed at arrival → evicted from the
    # waiting queue before any prefill, holding zero blocks.
    sched.add_request("r0", list(range(1, 33)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=8))
    sched.add_request("r1", list(range(2, 34)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=8, deadline_ms=0.001))
    produced = {}
    for _ in range(200):
        if not sched.has_work():
            break
        for seq, out in sched.step():
            produced.setdefault(seq.request_id, []).append(out)
    assert not sched.has_work()
    assert produced["r1"][-1].finish_reason == "timeout"
    assert [o.token_id for o in produced["r1"] if o.token_id >= 0] == []
    assert produced["r0"][-1].finish_reason in ("stop", "length")
    assert len([o for o in produced["r0"] if o.token_id >= 0]) == 8
    assert sched.timeouts_total == 1
    assert sched.allocator.num_active == 0

    # Mid-decode expiry: run a few steps, then lapse the deadline by hand
    # (deterministic — no wall-clock race) and prove the running row's
    # blocks come back.
    # Long budget: multi-step decode windows can retire many tokens per
    # step() call, so keep max_tokens far above what 3 calls can finish.
    sched.add_request("r2", list(range(3, 35)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=800, deadline_ms=60_000.0))
    for _ in range(3):
        sched.step()
    seq = sched.by_id["r2"]
    assert seq.block_ids, "r2 should hold KV blocks mid-decode"
    seq.deadline_ts = 0.0  # already past
    out = None
    for _ in range(10):
        stepped = sched.step()
        if stepped:
            out = stepped[-1][1]
            break
    assert out is not None and out.finish_reason == "timeout"
    assert sched.timeouts_total == 2
    assert sched.allocator.num_active == 0


# --- migration fold accounting (satellite) ------------------------------------
def test_migration_fold_decrements_budgets_and_clamps_cached():
    folded = _MigrationEngine._fold(
        {"token_ids": [1, 2, 3],
         "stop_conditions": {"max_tokens": 10, "deadline_ms": 1000.0}},
        [7, 8], time.monotonic() - 0.2,  # 200 ms already elapsed
    )
    assert folded["token_ids"] == [1, 2, 3, 7, 8]
    assert folded["stop_conditions"]["max_tokens"] == 8
    # Deadline budget shrank by the elapsed time (±scheduling slop).
    assert folded["stop_conditions"]["deadline_ms"] == pytest.approx(800.0, abs=100.0)

    # Folding again keeps decrementing against the ORIGINAL budget.
    folded2 = _MigrationEngine._fold(folded, [9], time.monotonic() - 0.5)
    assert folded2["stop_conditions"]["max_tokens"] == 7
    assert folded2["stop_conditions"]["deadline_ms"] == pytest.approx(500.0, abs=100.0)

    # cached_tokens honesty: a replay's warm hit covering prompt+folded
    # output clamps to the original prompt; duplicates are swallowed.
    out = {"token_ids": [5], "cached_tokens": 5}
    item = _MigrationEngine._honest_cached(out, out, orig_prompt_len=3,
                                           already_reported=False)
    assert item["cached_tokens"] == 3
    dup = {"token_ids": [], "cached_tokens": 3}
    assert _MigrationEngine._honest_cached(dup, dup, 3, already_reported=True) is None


async def test_migration_exhausted_metadata_carries_partial_count():
    """Exhausted migration annotates the context with the partial token
    count — what the frontend's structured 502 reports."""
    drt = await DistributedRuntime.detached()
    try:
        _, client, router, engine, workers = await chaos_stack(drt, "chaosx", migration_limit=1)
        faults.arm(faults.FaultInjector([
            {"site": "worker.frame", "kind": "stream_drop", "count": 0, "after": 2},
        ], seed=7))
        ctx = Context()
        got = []
        with pytest.raises(StreamDisconnect):
            async for item in engine.generate(req(range(10), max_tokens=8), ctx):
                data = item.data if hasattr(item, "data") else item
                if isinstance(data, dict):
                    got.extend(data.get("token_ids") or [])
        # Attempt 1 streams 2 frames before the drop; the replay's pass
        # counter is already past `after`, so it drops on its first frame.
        assert ctx.metadata["migration"]["tokens_emitted"] == len(got) == 2
        assert ctx.metadata["migration"]["attempts"] == 1
        assert_drained(workers)
    finally:
        await drt.shutdown()


# --- HTTP frontend failure mapping --------------------------------------------
async def _http_service(manager):
    from dynamo_tpu.llm.http.service import HttpService

    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service


async def test_http_503_with_retry_after_when_no_instances():
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager

    class NoWorkersEngine:
        availability_probe = staticmethod(lambda: 0)

        def generate(self, request, context):  # pragma: no cover — never routed
            raise AssertionError("must not be called")

    manager = ModelManager()
    manager.add_model("chat", "m", NoWorkersEngine())
    service = await _http_service(manager)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "m", "messages": [{"role": "user", "content": "hi"}]}
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 503
                assert r.headers["Retry-After"] == "1"
                data = await r.json()
                assert data["error"]["type"] == "service_unavailable"
    finally:
        await service.stop()


async def test_http_504_deadline_with_partial_usage():
    """Client ``timeout`` rides the wire as a deadline budget; the mocker
    evicts at expiry and the unary answer is a 504 carrying the partial
    token count in usage."""
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    mock = MockTpuEngine(MockEngineArgs(
        speedup_ratio=1.0, itl_base_ms=60.0, num_blocks=128))
    manager = ModelManager()
    manager.add_model("chat", "m", build_local_pipeline(ByteTokenizer(), mock))
    service = await _http_service(manager)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 100, "timeout": 0.4}
            t0 = time.monotonic()
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                elapsed = time.monotonic() - t0
                assert r.status == 504, await r.text()
                data = await r.json()
                assert data["error"]["type"] == "timeout_error"
                assert 0 < data["usage"]["completion_tokens"] < 100, data["usage"]
                assert elapsed < 3.0
        assert mock.timeouts_total == 1
        assert mock.allocator.num_active == 0
        # Bad timeout values are structured 400s.
        async with aiohttp.ClientSession() as s:
            body = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
                    "timeout": -1}
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 400
    finally:
        await service.stop()


async def test_http_504_watchdog_on_hung_worker():
    """A worker that never produces a frame cannot hold the client past the
    deadline: the frontend's own watchdog answers 504."""
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    class HungEngine:
        async def generate(self, request, context):
            await asyncio.sleep(600)
            yield {}

    manager = ModelManager()
    manager.add_model("chat", "m", build_local_pipeline(ByteTokenizer(), HungEngine()))
    service = await _http_service(manager)
    try:
        async with aiohttp.ClientSession() as s:
            body = {"model": "m", "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4, "timeout": 0.3}
            t0 = time.monotonic()
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                elapsed = time.monotonic() - t0
                assert r.status == 504
                data = await r.json()
                assert data["usage"]["completion_tokens"] == 0
            # deadline (0.3) + grace (0.5) + bounded reap — not 600s.
            assert elapsed < 5.0
    finally:
        await service.stop()


async def test_http_502_on_exhausted_migration_with_partial_tokens():
    import aiohttp

    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_routed_pipeline
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("chaoshttp").component("w").endpoint("gen")
        await spawn_worker(drt, ep)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client, retry=RetryPolicy(seed=0))
        pipeline = build_routed_pipeline(ByteTokenizer(), router, migration_limit=1)
        manager = ModelManager()
        manager.add_model("completions", "m", pipeline)
        service = await _http_service(manager)
        # Every attempt drops after 2 frames.
        faults.arm(faults.FaultInjector([
            {"site": "worker.frame", "kind": "stream_drop", "count": 0, "after": 2},
        ], seed=7))
        try:
            async with aiohttp.ClientSession() as s:
                body = {"model": "m", "prompt": "hello", "max_tokens": 8}
                async with s.post(f"http://127.0.0.1:{service.port}/v1/completions", json=body) as r:
                    assert r.status == 502, await r.text()
                    data = await r.json()
                    assert data["error"]["type"] == "bad_gateway"
                    assert data["error"]["partial_tokens"] == 2
                    assert data["error"]["migrations"] == 1
        finally:
            await service.stop()
    finally:
        await drt.shutdown()


async def test_model_survives_drain_of_one_backing_worker():
    """Two workers register the same model (per-instance model keys): the
    frontend watcher refcounts, so draining one worker must NOT drop the
    model — scale-down leaves the survivors serving."""
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.entrypoint import register_llm
    from dynamo_tpu.llm.model_card import ModelDeploymentCard

    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("chaosmw").component("w").endpoint("gen")
        card = ModelDeploymentCard(name="m", model_type="chat", kv_cache_block_size=16)
        mock_a = MockTpuEngine(MockEngineArgs())
        mock_b = MockTpuEngine(MockEngineArgs())
        handle_a, _ = await register_llm(drt, ep, mock_a, card,
                                         stats_handler=mock_a.stats_handler)
        handle_b, _ = await register_llm(drt, ep, mock_b, card,
                                         stats_handler=mock_b.stats_handler)

        manager = ModelManager()
        built = []

        async def factory(entry):
            built.append(entry.name)

            class _E:
                async def generate(self, request, context):
                    yield {}

            return _E()

        watcher = ModelWatcher(drt, manager, factory)
        await watcher.start()
        assert manager.get("chat", "m") is not None
        assert built == ["m"]  # one pipeline, refcounted across both workers

        await handle_a.stop(drain=True)
        await asyncio.sleep(0.1)
        assert manager.get("chat", "m") is not None, (
            "draining one of two same-model workers dropped the model"
        )
        await handle_b.stop(drain=True)
        for _ in range(50):
            if manager.get("chat", "m") is None:
                break
            await asyncio.sleep(0.05)
        assert manager.get("chat", "m") is None, "last worker gone ⇒ model removed"
        await watcher.stop()
    finally:
        await drt.shutdown()


# --- elastic: faults during ratio shifts + degradation-ladder flips -----------
async def test_crash_during_ratio_shift_zero_token_loss():
    """A worker crash lands in the middle of a fleet-wide ratio shift (both
    workers' capacity dials reshaped while the stream is in flight): the
    migrated continuation is still bit-identical — the dial moves capacity,
    never tokens."""
    drt = await DistributedRuntime.detached()
    migrations = []
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaose1", on_migrate=lambda: migrations.append(1),
            speedup_ratio=1.0, itl_base_ms=20.0)
        faults.arm(faults.FaultInjector(
            [{"site": "worker.step", "kind": "crash", "after": 4}], seed=7))

        t0 = time.monotonic()
        stream = asyncio.create_task(collect(engine, req(range(10), max_tokens=16)))
        # Straddle the armed crash (fires on the 5th step, ~100ms in) with a
        # two-move ratio shift across the whole fleet.
        await asyncio.sleep(0.05)
        for mocker, _ in workers:
            mocker.set_capacity_dial(0.9)
        await asyncio.sleep(0.05)
        for mocker, _ in workers:
            mocker.set_capacity_dial(0.3)

        got, finish = await stream
        elapsed = time.monotonic() - t0
        assert got == list(range(10, 26)), got
        assert finish == "length"
        assert len(migrations) == 1
        assert faults.get_injector().to_stats()["faults_crash_total"] == 1
        assert elapsed < 10.0, f"recovery took {elapsed:.1f}s"
        for mocker, _ in workers:
            assert mocker.elastic_dial_changes_total == 2
        assert_drained(workers)
    finally:
        await drt.shutdown()


async def test_lease_loss_during_ratio_shift_migrates_exactly_once():
    """Lease expiry evicts a worker while a ratio shift sweeps the fleet:
    the router must still evict before the next route and the migrated
    stream loses nothing — a dial move is never an excuse for token loss."""
    drt = await DistributedRuntime.detached()
    migrations = []
    try:
        ep = drt.namespace("chaose2").component("w").endpoint("gen")
        victim, h_victim = await spawn_worker(
            drt, ep, lease_ttl_s=0.5, speedup_ratio=1.0, itl_base_ms=40.0)
        vid = h_victim.instance.instance_id
        faults.arm(faults.FaultInjector([
            {"site": "lease.keepalive", "kind": "lease_drop", "count": 0,
             "match": {"lease": f"{vid:x}"}},
        ], seed=7))
        survivor, h_surv = await spawn_worker(
            drt, ep, speedup_ratio=1.0, itl_base_ms=40.0)
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = PushRouter(client, retry=RetryPolicy(max_retries=2, backoff_base_s=0.01, seed=0))
        engine = Migration(2, on_migrate=lambda: migrations.append(1)).attach(RouterEngine(router))
        router._rr = sorted(client.instances).index(vid)

        stream_task = asyncio.create_task(collect(engine, req(range(10), max_tokens=60)))
        # The ratio shift lands while the victim's lease is already dying.
        await asyncio.sleep(0.1)
        victim.set_capacity_dial(0.8)
        survivor.set_capacity_dial(0.8)

        deadline = time.monotonic() + 5.0
        while vid in client.instances and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert vid not in client.instances, "lease expiry did not evict the instance"
        assert stream_task.done() is False, "stream should still be mid-flight"
        victim._crash_all()

        got, finish = await stream_task
        assert got == list(range(10, 70)), "migrated stream lost or duplicated tokens"
        assert finish == "length"
        assert len(migrations) == 1
        assert survivor.elastic_dial_changes_total == 1
        assert survivor.allocator.num_active == 0
    finally:
        await drt.shutdown()


async def test_crash_during_degrade_to_colocated_zero_token_loss():
    """The degradation ladder under fire: a saturated prefill pool degrades
    the request disagg→co-located, and the co-located worker then CRASHES
    mid-stream. The degraded leg rides the same router+migration machinery
    as any request — exact tokens, one migration, bounded recovery."""
    from dynamo_tpu.llm.disagg import DisaggDecodeHandler

    drt = await DistributedRuntime.detached()
    migrations = []
    try:
        _, client, router, engine, workers = await chaos_stack(
            drt, "chaose3", on_migrate=lambda: migrations.append(1),
            speedup_ratio=1.0, itl_base_ms=20.0)
        # A live prefill pool the probe declares saturated: the proactive
        # rung fires BEFORE any wire hop, so the pool stays untouched.
        prefill_ep = drt.namespace("chaose3").component("prefill").endpoint("gen")
        p_engine, p_handle = await spawn_worker(drt, prefill_ep)
        prefill_client = await prefill_ep.client()
        await prefill_client.wait_for_instances(1, timeout=5)
        handler = DisaggDecodeHandler(
            drt, engine, prefill_client,
            pool_load_probe=lambda: {"prefill_saturated": True})

        faults.arm(faults.FaultInjector(
            [{"site": "worker.step", "kind": "crash", "after": 4}], seed=7))
        t0 = time.monotonic()
        got, finish = await collect(handler, req(range(10), max_tokens=16))
        elapsed = time.monotonic() - t0

        assert got == list(range(10, 26)), got
        assert finish == "length"
        assert handler.degrade_disagg_to_colocated_total == 1
        assert handler.local_prefills == 1 and handler.remote_prefills == 0
        assert len(migrations) == 1, "the crash must fire inside the degraded leg"
        assert faults.get_injector().to_stats()["faults_crash_total"] == 1
        assert elapsed < 10.0, f"recovery took {elapsed:.1f}s"
        assert_drained(workers)
        assert p_engine.allocator.num_active == 0  # the pool never saw the request
    finally:
        await drt.shutdown()
