"""Logits-processing tests: stock processors + engine integration
(ref: dynamo.logits_processing examples)."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig, StopConditions
from dynamo_tpu.logits_processing import (
    AllowedTokensProcessor,
    LogitBiasProcessor,
    MinPProcessor,
    RepetitionPenaltyProcessor,
    TemperatureProcessor,
    apply_chain,
)


def test_repetition_penalty():
    logits = jnp.array([2.0, -1.0, 0.5, 3.0])
    proc = RepetitionPenaltyProcessor(penalty=2.0)
    out = np.asarray(proc([0, 1], logits))
    assert out[0] == 1.0  # positive → divided
    assert out[1] == -2.0  # negative → multiplied
    assert out[2] == 0.5 and out[3] == 3.0  # unseen untouched


def test_allowed_tokens_masks_everything_else():
    logits = jnp.zeros((10,))
    out = np.asarray(AllowedTokensProcessor(allowed=[3, 7])([], logits))
    kept = np.isfinite(out)
    assert kept[3] and kept[7] and kept.sum() == 2


def test_logit_bias_processor():
    logits = jnp.zeros((8,))
    out = np.asarray(LogitBiasProcessor({3: 5.0, "5": -2.0})([], logits))
    assert out[3] == 5.0 and out[5] == -2.0
    assert out[0] == 0.0 and out[7] == 0.0
    # Out-of-vocab ids are ignored, not an index error.
    out = np.asarray(LogitBiasProcessor({99: 5.0})([], logits))
    assert (out == 0.0).all()


async def test_engine_logit_bias_steers_greedy_decode():
    """OpenAI logit_bias via sampling_options: +100 forces the biased token
    under greedy decode; −100 bans the otherwise-argmax tokens."""
    import asyncio

    from dynamo_tpu.runtime.engine import Context

    engine = TpuEngine.build(
        EngineArgs(
            model="tiny", dtype="float32",
            scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64],
                                      decode_buckets=[1, 2, 4]),
        )
    )

    async def run(bias):
        so = {"temperature": 0}
        if bias is not None:
            so["logit_bias"] = bias
        req = {"token_ids": list(range(10)), "sampling_options": so,
               "stop_conditions": {"max_tokens": 4, "ignore_eos": True}}
        toks = []
        async for frame in engine.generate(req, Context()):
            toks += frame["token_ids"]
        return toks

    try:
        plain = await run(None)
        forced = await run({7: 100.0})
        assert forced == [7, 7, 7, 7], forced
        banned = await run({t: -100.0 for t in set(plain)})
        assert not (set(banned) & set(plain)), (plain, banned)
    finally:
        await engine.stop()


def test_min_p():
    logits = jnp.log(jnp.array([0.6, 0.3, 0.05, 0.05]))
    out = np.asarray(MinPProcessor(min_p=0.2)([], logits))
    assert np.isfinite(out[0]) and np.isfinite(out[1])
    assert not np.isfinite(out[2]) and not np.isfinite(out[3])


def test_chain_order():
    logits = jnp.array([1.0, 2.0, 3.0])
    out = apply_chain([TemperatureProcessor(2.0), AllowedTokensProcessor(allowed=[2])], [], logits)
    out = np.asarray(out)
    assert out[2] == 1.5 and not np.isfinite(out[0])


def test_engine_respects_allowed_tokens():
    """Greedy decode constrained to one token must emit only that token."""
    import asyncio

    async def run():
        engine = TpuEngine.build(
            EngineArgs(
                model="tiny",
                dtype="float32",
                scheduler=SchedulerConfig(num_blocks=32, prefill_buckets=[16, 32], decode_buckets=[1, 2]),
            )
        )
        try:
            sched = engine.scheduler
            seq = sched.add_request(
                "r1",
                list(range(10, 20)),
                SamplingParams(temperature=0.0, logits_processors=[AllowedTokensProcessor(allowed=[42])]),
                StopConditions(max_tokens=4),
            )
            import queue as _q

            class Q:
                def __init__(self):
                    self.items = []

                def put_nowait(self, x):
                    self.items.append(x)

            seq.out_queue = Q()
            collected = []
            for _ in range(8):
                collected.extend(out for s, out in sched.step() if s is seq)
                if collected and collected[-1].finished:
                    break
            toks = [o.token_id for o in collected if o.token_id >= 0]
            assert toks == [42] * len(toks) and len(toks) == 4
        finally:
            await engine.stop()

    asyncio.run(run())
