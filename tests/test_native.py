"""Parity tests: C++ extension (native/dynamo_tpu_native.cc) vs pure Python.

The native module is the TPU build's equivalent of the reference's native
hot paths (lib/tokens/src/lib.rs hashing; lib/llm/src/kv_router/indexer.rs
RadixTree). Semantics must be identical — same hashes bit-for-bit, same
overlap scores, same snapshot format.
"""

import json
import random

import pytest

from dynamo_tpu.native import get_native

native = get_native()
pytestmark = pytest.mark.skipif(native is None, reason="native extension not built")


def test_hash_parity():
    import struct

    import xxhash

    rng = random.Random(0)
    for n in (0, 1, 5, 16, 64, 257, 4096):
        toks = [rng.randrange(0, 2**31) for _ in range(n)]
        buf = struct.pack(f"<{n}I", *toks)
        for seed in (0, 7, 0x6462_6C6B):
            assert native.hash_tokens(toks, seed) == xxhash.xxh3_64_intdigest(buf, seed=seed)


def test_block_hash_parity():
    from dynamo_tpu.llm import tokens as T

    rng = random.Random(1)
    toks = [rng.randrange(0, 128000) for _ in range(1000)]
    for bs in (16, 64, 128):
        nat = native.hash_token_blocks(toks, bs, T.ROOT_SEED)
        # Pure-python chained loop (bypass the native fast path).
        seed = T.ROOT_SEED
        ref = []
        for i in range(len(toks) // bs):
            seed = T.hash_tokens(toks[i * bs : (i + 1) * bs], seed)
            ref.append(seed)
        assert nat == ref


def _random_ops(rng, n_workers=4, n_ops=500):
    """A reproducible stream of radix events."""
    chains = []
    ops = []
    for _ in range(n_ops):
        r = rng.random()
        if r < 0.5 or not chains:
            # store a fresh or extending chain
            if chains and rng.random() < 0.5:
                parent_chain = rng.choice(chains)
                parent = parent_chain[-1]
                new = [rng.randrange(1, 2**63) for _ in range(rng.randrange(1, 4))]
                chains.append(parent_chain + new)
                ops.append(("stored", rng.randrange(n_workers), new, parent))
            else:
                new = [rng.randrange(1, 2**63) for _ in range(rng.randrange(1, 5))]
                chains.append(new)
                ops.append(("stored", rng.randrange(n_workers), new, None))
        elif r < 0.8:
            chain = rng.choice(chains)
            k = rng.randrange(1, len(chain) + 1)
            ops.append(("removed", rng.randrange(n_workers), chain[-k:], None))
        else:
            ops.append(("cleared", rng.randrange(n_workers), [], None))
    return chains, ops


def test_radix_parity_random_ops():
    from dynamo_tpu.llm.kv_router.indexer import NativeRadixTree, RadixTree

    rng = random.Random(42)
    chains, ops = _random_ops(rng)
    py, nat = RadixTree(), NativeRadixTree()
    for kind, w, hashes, parent in ops:
        if kind == "stored":
            py.apply_stored(w, hashes, parent)
            nat.apply_stored(w, hashes, parent)
        elif kind == "removed":
            py.apply_removed(w, hashes)
            nat.apply_removed(w, hashes)
        else:
            py.remove_worker(w)
            nat.remove_worker(w)
        assert py.size() == nat.size()
    assert py.workers() == nat.workers()
    for chain in chains:
        a = py.find_matches(chain).scores
        b = nat.find_matches(chain).scores
        assert a == b


def test_radix_snapshot_roundtrip_cross_impl():
    from dynamo_tpu.llm.kv_router.indexer import NativeRadixTree, RadixTree

    nat = NativeRadixTree()
    nat.apply_stored(1, [10, 20, 30], None)
    nat.apply_stored(2, [10, 20], None)
    nat.apply_stored(2, [99], 20)
    # Native dump → python load and vice versa.
    py = RadixTree.load(nat.dump())
    assert py.find_matches([10, 20, 30]).scores == nat.find_matches([10, 20, 30]).scores
    nat2 = NativeRadixTree.load(py.dump())
    assert nat2.find_matches([10, 20, 99]).scores == nat.find_matches([10, 20, 99]).scores
    # Snapshot format is stable JSON records.
    recs = json.loads(nat.dump())
    assert all(set(r) == {"h", "p", "w"} for r in recs)


def test_indexer_uses_native_by_default():
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, NativeRadixTree

    idx = KvIndexer(block_size=16)
    assert isinstance(idx.tree, NativeRadixTree)
    idx.apply_event(7, {"kind": "stored", "block_hashes": [1, 2], "parent_hash": None})
    assert idx.find_matches([1, 2]).scores == {7: 2}
    idx.apply_event(7, {"kind": "cleared"})
    assert idx.find_matches([1, 2]).scores == {}
