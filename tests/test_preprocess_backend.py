"""Unit tests: chat-template rendering, tokenization, incremental
detokenization, stop-string jailing."""

import pytest

from dynamo_tpu.llm.backend import Backend, StopStringJail
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor, PromptFormatter
from dynamo_tpu.llm.protocols.common import LLMEngineOutput
from dynamo_tpu.llm.tokenizer import ByteTokenizer, DecodeStream
from dynamo_tpu.runtime.engine import Annotated, Context


def test_prompt_formatter_default_template():
    f = PromptFormatter()
    out = f.render([{"role": "system", "content": "be brief"}, {"role": "user", "content": "hi"}])
    assert "<|system|>" in out and "be brief" in out
    assert out.rstrip().endswith("<|assistant|>")


def test_prompt_formatter_custom_template():
    f = PromptFormatter("{% for m in messages %}[{{m.role}}]{{m.content}}{% endfor %}")
    assert f.render([{"role": "user", "content": "x"}]) == "[user]x"


def test_preprocess_chat():
    p = OpenAIPreprocessor(ByteTokenizer())
    req, prompt = p.preprocess(
        {
            "model": "m",
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 7,
            "temperature": 0.5,
            "stop": ["\n\n"],
        }
    )
    assert req.token_ids == ByteTokenizer().encode(prompt)
    assert req.stop_conditions["max_tokens"] == 7
    assert req.stop_conditions["stop"] == ["\n\n"]
    assert req.sampling_options["temperature"] == 0.5


def test_preprocess_completion_with_token_ids():
    p = OpenAIPreprocessor(ByteTokenizer())
    req, prompt = p.preprocess({"model": "m", "prompt": [5, 6, 7]})
    assert req.token_ids == [5, 6, 7] and prompt is None


def test_decode_stream_incremental():
    tok = ByteTokenizer()
    ds = DecodeStream(tok)
    text = "héllo wörld"
    ids = tok.encode(text)
    out = ""
    for i in ids:
        out += ds.step([i])
    assert out == text  # multibyte chars held until complete


def test_stop_jail_immediate_hit():
    jail = StopStringJail(["STOP"])
    emit, hit = jail.feed("abcSTOPxyz")
    assert emit == "abc" and hit


def test_stop_jail_split_across_deltas():
    jail = StopStringJail(["STOP"])
    emit, hit = jail.feed("abcST")
    assert emit == "abc" and not hit
    emit, hit = jail.feed("OP")
    assert emit is None and hit


def test_stop_jail_false_alarm_releases():
    jail = StopStringJail(["STOP"])
    emit, hit = jail.feed("xyST")
    assert emit == "xy" and not hit
    emit, hit = jail.feed("ATIC")
    assert emit == "STATIC" and not hit


async def test_backend_stop_string_ends_stream():
    tok = ByteTokenizer()
    backend = Backend(tok)

    async def engine_stream():
        for chunk in ["he", "llo ST", "OP more"]:
            yield Annotated(data=LLMEngineOutput(token_ids=tok.encode(chunk)).to_wire())
        yield Annotated(data=LLMEngineOutput(finish_reason="length").to_wire())

    ctx = Context()
    request = {"stop_conditions": {"stop": ["STOP"]}}
    outs = []
    async for item in backend.transform_response(engine_stream(), request, ctx):
        outs.append(LLMEngineOutput.from_wire(item.data))
    text = "".join(o.text or "" for o in outs)
    assert text == "hello "
    assert outs[-1].finish_reason == "stop"
    assert ctx.is_stopped()  # backend propagates abort upstream
