"""Ragged paged-attention megakernel: interpreter-mode parity vs the XLA
gather path over head layouts (GQA/MQA/MHA), ragged edge cases (length-1
decode rows mixed with chunk rows, short sequences in wide buckets, page-
boundary prefix lengths, dead scratch-block-0 slots), the int8-KV
dequant-in-VMEM path, and the fused N-step decode window (token AND KV
cache-content parity vs ``decode_multi``, exactly ONE pallas launch per
window, 0 post-warmup compiles at the scheduler).

Everything runs the Pallas interpreter on CPU (tier-1 CI); the kernels are
the same code the TPU auto-selection dispatches.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine.attention import megakernel as mk
from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

CFG = get_config("tiny")  # GQA: 4 heads over 2 KV heads
MEGA = CFG.replace(attention_impl="megakernel")


def _fresh(cfg, num_blocks=64):
    c = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.float32)
    return c.k, c.v


def _prefill(params, cfg, k, v, toks, table, cache_len=0):
    t = jnp.asarray(np.asarray(toks, np.int32))
    return jax.jit(
        lambda p, k, v: llama.prefill(
            p, cfg, k, v, t, jnp.int32(len(toks)), jnp.int32(cache_len), table
        )
    )(params, k, v)


# ---------------------------------------------------------------------------
# Head layouts: GQA / MHA / MQA
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kvh", [2, 4, 1], ids=["gqa", "mha", "mqa"]
)
def test_decode_parity_head_layouts(kvh):
    """Megakernel decode logits + written KV match the XLA gather for every
    head layout the block-diagonal GQA fold must cover."""
    base = CFG.replace(num_kv_heads=kvh)
    params = llama.init_params(base, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(1)
    table = jnp.asarray(np.arange(1, 5, dtype=np.int32))
    toks = rng.integers(1, 255, size=30)

    B = 3
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 30, jnp.int32)
    tables_d = jnp.asarray(np.tile(np.arange(1, 5, dtype=np.int32), (B, 1)))
    active = jnp.ones((B,), bool)

    def run(cfg):
        k, v = _fresh(cfg)
        _, k, v = _prefill(params, cfg, k, v, toks, table)
        return jax.jit(
            lambda p, k, v: llama.decode(p, cfg, k, v, dtoks, pos, tables_d, active)
        )(params, k, v)

    lg_g, kg, vg = run(base)
    lg_m, km, vm = run(base.replace(attention_impl="megakernel"))
    np.testing.assert_allclose(np.asarray(lg_g), np.asarray(lg_m), atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg), np.asarray(km), atol=2e-5)
    np.testing.assert_allclose(np.asarray(vg), np.asarray(vm), atol=2e-5)


# ---------------------------------------------------------------------------
# Ragged edge cases
# ---------------------------------------------------------------------------


def test_prefill_chunk_with_prefix_parity():
    """A (start, len) chunk row over a cached prefix — including a chunk
    that starts exactly ON a page boundary — matches the gather path."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(2)
    table = jnp.asarray(np.arange(1, 6, dtype=np.int32))
    first = rng.integers(1, 255, size=32)  # ends exactly at 2 pages (bs=16)
    second = rng.integers(1, 255, size=19)

    def run(cfg):
        k, v = _fresh(cfg)
        lg1, k, v = _prefill(params, cfg, k, v, first, table)
        lg2, k, v = _prefill(params, cfg, k, v, second, table, cache_len=32)
        return lg1, lg2, k, v

    g1, g2, kg, vg = run(CFG)
    m1, m2, km, vm = run(MEGA)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(m1), atol=2e-4)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(m2), atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg), np.asarray(km), atol=2e-5)


def test_mixed_step_parity_chunk_plus_decode_rows():
    """The whole mixed step — a ragged chunk row AND length-1 decode rows in
    one launch — matches the two-shape XLA path, including padded chunk
    queries (len < bucket) and an INACTIVE decode lane. Scratch block 0 is
    excluded from the KV comparison: dead rows sink different garbage
    there by design and it is never handed out or read."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(3)
    toks = rng.integers(1, 255, size=21)  # short seq: 21 tokens in 2 pages
    p_table = jnp.asarray(np.array([5, 6, 7, 8], np.int32))

    B = 4  # 3 live decode rows + 1 dead lane
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    dpos = jnp.asarray(np.array([30, 16, 7, 0], np.int32))  # incl. page-exact 16
    # Wide bucket for a short row: row 2 (7 tokens) rides an 8-wide table.
    tables_d = jnp.asarray(
        np.stack([np.r_[1:5, 0, 0, 0, 0], np.r_[9:13, 0, 0, 0, 0],
                  np.r_[13:17, 0, 0, 0, 0], np.zeros(8, np.int64)]).astype(np.int32)
    )
    active = jnp.asarray(np.array([True, True, True, False]))

    chunk = np.zeros((16,), np.int32)
    chunk[:9] = rng.integers(1, 255, size=9)

    # Fixed prompts so both impls seed bit-identical caches. The chunk
    # sequence's 21-token cached prefix (toks above) lives at blocks 5-8.
    seed_prompts = [
        (toks, np.arange(5, 9)),
        (rng.integers(1, 255, size=30), np.arange(1, 5)),
        (rng.integers(1, 255, size=16), np.arange(9, 13)),
        (rng.integers(1, 255, size=7), np.arange(13, 17)),
    ]

    def run(cfg):
        k, v = _fresh(cfg)
        for toks_s, tbl in seed_prompts:
            _, k, v = _prefill(params, cfg, k, v, toks_s,
                               jnp.asarray(tbl.astype(np.int32)))
        return jax.jit(
            lambda p, k, v: llama.mixed_step(
                p, cfg, k, v, jnp.asarray(chunk), jnp.int32(9), jnp.int32(21),
                p_table, dtoks, dpos, tables_d, active,
            )
        )(params, k, v)

    lg_g, kg, vg = run(CFG)
    lg_m, km, vm = run(MEGA)
    # Live rows only: logits row 0 is the chunk, rows 1..3 the live decode
    # lanes. The dead lane's logits are garbage in BOTH impls (masked
    # softmax junk vs kernel zeros) and the scheduler never reads them.
    np.testing.assert_allclose(np.asarray(lg_g)[:4], np.asarray(lg_m)[:4], atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg)[:, 1:], np.asarray(km)[:, 1:], atol=2e-5)
    np.testing.assert_allclose(np.asarray(vg)[:, 1:], np.asarray(vm)[:, 1:], atol=2e-5)


def test_dead_queries_return_zeros():
    """Dead ragged rows (meta active=0) read nothing and return exact zeros
    from the kernel — the pl.when skip, not masked softmax garbage."""
    kvh, hd, bs = 2, 16, 16
    H = 4
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((3, H, hd)).astype(np.float32))
    ke = jnp.asarray(rng.standard_normal((3, kvh, hd)).astype(np.float32))
    k_pages = jnp.asarray(rng.standard_normal((6, bs, kvh, hd)).astype(np.float32))
    v_pages = jnp.asarray(rng.standard_normal((6, bs, kvh, hd)).astype(np.float32))
    tables = jnp.asarray(np.array([[1, 2], [3, 4], [0, 0]], np.int32))
    meta = mk.build_meta(
        jnp.asarray(np.array([0, 1, 2], np.int32)),
        jnp.asarray(np.array([20, 20, 0], np.int32)),
        jnp.asarray(np.array([0, 1, 2], np.int32)),
        jnp.asarray(np.array([1, 2, 2], np.int32)),  # row 2: no fresh keys either
        jnp.asarray(np.array([1, 1, 0], np.int32)),  # row 2 dead
    )
    out = mk.ragged_paged_attention(
        q, ke, ke, k_pages, v_pages, tables, meta,
        num_kv_heads=kvh, block_size=bs, interpret=True,
    )
    assert np.all(np.asarray(out)[2] == 0.0), "dead query must return zeros"
    assert np.all(np.isfinite(np.asarray(out)[:2]))


# ---------------------------------------------------------------------------
# int8 KV: dequant-in-VMEM path
# ---------------------------------------------------------------------------


def test_int8_kv_megakernel_parity():
    """Megakernel attention over a QuantKv cache (int8 codes + per-(token,
    head) scales dequantized in VMEM) matches the gather path reading the
    SAME quantized cache — bitwise-equal inputs, so tolerance is float
    accumulation, not quantization error."""
    cfg8_g = CFG.replace(kv_cache_dtype="int8")
    cfg8_m = cfg8_g.replace(attention_impl="megakernel")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(5)
    table = jnp.asarray(np.arange(1, 5, dtype=np.int32))
    toks = rng.integers(1, 255, size=30)

    B = 2
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 30, jnp.int32)
    tables_d = jnp.asarray(np.tile(np.arange(1, 5, dtype=np.int32), (B, 1)))
    active = jnp.ones((B,), bool)

    def run(cfg):
        k, v = _fresh(cfg)
        _, k, v = _prefill(params, cfg, k, v, toks, table)
        lg, k, v = jax.jit(
            lambda p, k, v: llama.decode(p, cfg, k, v, dtoks, pos, tables_d, active)
        )(params, k, v)
        return lg

    lg_g = run(cfg8_g)
    lg_m = run(cfg8_m)
    np.testing.assert_allclose(np.asarray(lg_g), np.asarray(lg_m), atol=5e-4)


def test_paged_int8_degrades_to_gather():
    """attention_impl='paged' + int8 KV no longer raises at config
    validation; the engine degrades to the gather with a warning."""
    cfg = CFG.replace(attention_impl="paged", kv_cache_dtype="int8")  # no raise
    cache = KvCacheArrays.create(cfg, num_blocks=8, dtype=jnp.float32)
    assert llama.resolve_attention_impl(cfg, cache.k) == "gather"
    # megakernel keeps the fused path for int8.
    cfg_m = CFG.replace(attention_impl="megakernel", kv_cache_dtype="int8")
    assert llama.resolve_attention_impl(cfg_m, cache.k) == "megakernel"


def test_attention_impl_validation():
    with pytest.raises(ValueError, match="attention_impl"):
        CFG.replace(attention_impl="bogus")
    for ok in ("auto", "gather", "paged", "megakernel"):
        assert CFG.replace(attention_impl=ok).attention_impl == ok


# ---------------------------------------------------------------------------
# Fused N-step decode window
# ---------------------------------------------------------------------------


def test_fused_window_parity_and_single_launch():
    """One fused launch serves an entire greedy decode window: tokens AND
    written KV cache contents match greedy ``decode_multi``, and the traced
    executable contains exactly ONE pallas_call site."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(6)
    B, steps = 3, 4
    toks = rng.integers(1, 255, size=21)
    tables = np.stack([np.arange(1 + 4 * b, 5 + 4 * b, dtype=np.int32) for b in range(B)])

    k, v = _fresh(CFG)
    for b in range(B):
        _, k, v = _prefill(params, CFG, k, v, toks, jnp.asarray(tables[b]))

    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 21, jnp.int32)
    active = jnp.ones((B,), bool)
    t_j = jnp.asarray(tables)

    n0 = mk.trace_launch_count()
    toks_f, kf, vf = llama.decode_multi_fused(
        params, MEGA, k, v, dtoks, pos, t_j, active, num_steps=steps
    )
    assert mk.trace_launch_count() - n0 == 1, "fused window must be ONE launch"

    greedy = (jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
              jnp.ones((B,), jnp.float32))
    toks_r, kr, vr = jax.jit(
        lambda p, k, v: llama.decode_multi(
            p, CFG, k, v, dtoks, pos, t_j, active, *greedy,
            jax.random.PRNGKey(9), steps,
        )
    )(params, k, v)
    np.testing.assert_array_equal(np.asarray(toks_f), np.asarray(toks_r))
    np.testing.assert_allclose(
        np.asarray(kf)[:, 1:], np.asarray(kr)[:, 1:], atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(vf)[:, 1:], np.asarray(vr)[:, 1:], atol=2e-4
    )


def test_scheduler_fused_window_e2e():
    """Scheduler end-to-end with attention_impl='megakernel': greedy token
    streams match the gather scheduler, every decode window dispatches as
    ONE pallas launch (flight-recorder gauge == 1), and a warmed scheduler
    compiles NOTHING mid-traffic."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(impl, warm):
        sched = Scheduler(CFG.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=8, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        if warm:
            sched.warmup(ctx_tokens=64)
            sched.flight.mark_warmup_done(warmed=True)
        toks = {}
        for i in range(3):
            sched.add_request(f"r{i}", list(range(1 + i, 25 + i)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=18, ignore_eos=True))
        for _ in range(200):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return sched, toks

    s_m, t_m = run("megakernel", warm=True)
    s_g, t_g = run("gather", warm=False)
    assert t_m == t_g, "megakernel scheduler must emit identical greedy tokens"
    assert s_m._use_fused_window
    assert s_m.flight.fused_windows_total > 0
    assert s_m.flight.fused_window_pallas_launches == 1
    assert s_m.flight.compiles_after_warmup_total == 0, (
        f"post-warmup compiles: {s_m.flight.post_warmup_keys}"
    )
    stats = s_m.flight.to_stats()
    assert stats["fused_window_pallas_launches"] == 1
    assert stats["fused_windows_total"] == s_m.flight.fused_windows_total


# ---------------------------------------------------------------------------
# Flight recorder: paged-path cost model + mixed-step phase split
# ---------------------------------------------------------------------------


def test_cost_model_paged_vs_gather_bytes():
    from dynamo_tpu.engine.flight_recorder import StepCostModel

    gather = StepCostModel(1000, 2000, 10.0, peak_flops=1e12, peak_bw=1e11,
                           kv_read_factor=3.0)
    paged = StepCostModel(1000, 2000, 10.0, peak_flops=1e12, peak_bw=1e11,
                          kv_read_factor=1.0)
    fg, bg = gather.step_cost(4, 100)
    fp, bp = paged.step_cost(4, 100)
    assert fg == fp  # FLOPs don't depend on the attention path
    # gather: 2000 + 3*100*10 + 4*10; paged: 2000 + 100*10 + 4*10
    assert bg - bp == pytest.approx(2 * 100 * 10.0)
    # A decode_multi window streams params once per step; the fused window
    # streams them once per window.
    _, b_loop = paged.step_cost(32, 800, param_passes=8.0)
    _, b_fused = paged.step_cost(32, 100, param_passes=1.0)
    assert b_loop - b_fused == pytest.approx(7 * 2000 + 700 * 10.0)


def test_mixed_step_phase_split():
    """record_mixed_step books the chunk into the prefill roofline and the
    decode rows into decode — both gauges move, and the mixed histogram
    still counts the step."""
    from dynamo_tpu.engine.flight_recorder import FlightRecorder, StepCostModel

    fr = FlightRecorder()
    fr.set_cost_model(StepCostModel(10_000, 20_000, 64.0,
                                    peak_flops=1e12, peak_bw=1e11))
    fr.record_mixed_step(0.01, prefill_tokens=128, decode_tokens=8,
                         kv_read_prefill=256, kv_read_decode=4096)
    util = fr.utilization()
    assert util["prefill"][0] > 0 and util["decode"][1] > 0
    assert "mixed" not in util  # cost split entirely into the real phases
    stats = fr.to_stats()
    assert stats["step_mixed_steps_total"] == 1
    assert stats["step_mixed_tokens_total"] == 136
    assert stats["step_prefill_flops_total"] > 0
    assert stats["step_decode_bytes_total"] > 0


# ---------------------------------------------------------------------------
# In-kernel sampling epilogue: fused window vs the sync uniforms replay
# ---------------------------------------------------------------------------


def _window_uniforms(B, steps, seed=11):
    from dynamo_tpu.engine.sampling import make_window_uniforms

    return make_window_uniforms(
        jax.random.PRNGKey(seed),
        jnp.zeros((B,), jnp.int32), jnp.zeros((B,), jnp.int32),
        jnp.zeros((B,), bool), steps,
    )


@pytest.mark.parametrize(
    "B,steps",
    [(8, 4), pytest.param(32, 2, marks=pytest.mark.slow)],
    ids=["b8", "b32"],
)
def test_fused_window_sampled_parity(B, steps):
    """The in-kernel sampling epilogue (temperature + top-k/top-p + inverse
    CDF) picks BIT-IDENTICAL tokens to ``decode_multi`` replaying the same
    uniforms, across mixed per-row params covering the threshold edges:
    greedy (temp 0), k=1 (degenerate top-k), p=1.0 (top-p off), k>vocab
    (clamps to full vocab), and plain temp>0. Written KV matches and the
    whole window is still ONE launch."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(7)
    toks = rng.integers(1, 255, size=21)
    tables = np.stack(
        [np.arange(1 + 4 * b, 5 + 4 * b, dtype=np.int32) for b in range(B)]
    )

    k, v = _fresh(CFG, num_blocks=4 * B + 2)
    for b in range(B):
        _, k, v = _prefill(params, CFG, k, v, toks, jnp.asarray(tables[b]))

    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 21, jnp.int32)
    active = jnp.ones((B,), bool)
    t_j = jnp.asarray(tables)

    # Per-row params cycling through every filter edge the shared
    # _exact_thresholds reference must hold at.
    edge = [
        (0.0, 0, 1.0),      # greedy row -> one-hot dist, argmax pick
        (0.9, 1, 1.0),      # k=1: top-k degenerates to argmax
        (0.8, 0, 1.0),      # p=1.0: top-p off entirely
        (0.7, 999, 0.95),   # k > vocab: clamps to full vocab
        (1.3, 20, 0.9),     # plain joint top-k/top-p
    ]
    rows = [edge[i % len(edge)] for i in range(B)]
    temps = jnp.asarray([r[0] for r in rows], jnp.float32)
    tks = jnp.asarray([r[1] for r in rows], jnp.int32)
    tps = jnp.asarray([r[2] for r in rows], jnp.float32)
    unif = _window_uniforms(B, steps)

    n0 = mk.trace_launch_count()
    toks_f, kf, vf = llama.decode_multi_fused(
        params, MEGA, k, v, dtoks, pos, t_j, active, num_steps=steps,
        temps=temps, top_ks=tks, top_ps=tps, uniforms=unif, sampled=True,
    )
    assert mk.trace_launch_count() - n0 == 1, "sampled window must be ONE launch"

    toks_r, kr, vr = jax.jit(
        lambda p, k, v: llama.decode_multi(
            p, CFG, k, v, dtoks, pos, t_j, active, temps, tks, tps,
            jax.random.PRNGKey(9), steps, uniforms=unif,
        )
    )(params, k, v)
    np.testing.assert_array_equal(np.asarray(toks_f), np.asarray(toks_r))
    np.testing.assert_allclose(
        np.asarray(kf)[:, 1:], np.asarray(kr)[:, 1:], atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(vf)[:, 1:], np.asarray(vr)[:, 1:], atol=2e-4
    )


@pytest.mark.slow  # interpret-mode Pallas e2e; the CI `fused-sampling`
# job gates the same invariants through bench.py in its own budget
def test_scheduler_fused_sampled_e2e():
    """Warmed megakernel scheduler serves seeded temp>0 traffic entirely on
    the fused sampled window: the sampled-variant counter advances, ZERO
    post-warmup compiles over the enlarged (sampled) key space, and the
    same request seeds reproduce the same tokens on a fresh scheduler."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run():
        sched = Scheduler(MEGA, params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=8, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        sched.warmup(ctx_tokens=64)
        sched.flight.mark_warmup_done(warmed=True)
        toks = {}
        for i in range(3):
            sched.add_request(
                f"r{i}", list(range(1 + i, 25 + i)),
                SamplingParams(temperature=0.8, top_k=20, top_p=0.9, seed=7 + i),
                StopConditions(max_tokens=10, ignore_eos=True),
            )
        for _ in range(200):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return sched, toks

    s1, t1 = run()
    assert s1.flight.fused_sampled_windows_total > 0
    assert s1.flight.compiles_after_warmup_total == 0, (
        f"post-warmup compiles: {s1.flight.post_warmup_keys}"
    )
    assert all(len(v) == 10 for v in t1.values())
    _, t2 = run()
    assert t1 == t2, "seeded sampling on the fused path must be reproducible"


@pytest.mark.slow  # interpret-mode Pallas e2e; the CI `fused-sampling`
# job gates the same invariants through bench.py in its own budget
def test_scheduler_guided_fused_parity():
    """Guided rows ride the fused window (on-chip bitmask + next-state FSM
    advance) and emit the SAME schema-constrained tokens as the gather
    scheduler's host-FSM sync path — with zero post-warmup compiles."""
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    pattern = '\\{"city": "(SF|NY)"\\}'

    def run(impl, warm, steps):
        sched = Scheduler(CFG.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=steps, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
            guided_pool_rows=64,
        ), dtype=jnp.float32, eos_token_ids=[0])
        sched.attach_guided(ByteTokenizer())
        if warm:
            sched.warmup(ctx_tokens=64)
            sched.flight.mark_warmup_done(warmed=True)
        toks = {}
        for i in range(2):
            sched.add_request(
                f"g{i}", list(range(5 + i, 21 + i)),
                SamplingParams(temperature=0.0), StopConditions(max_tokens=32),
                guided={"kind": "regex", "pattern": pattern},
            )
        for _ in range(300):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return sched, toks

    s_m, t_m = run("megakernel", warm=True, steps=8)
    s_g, t_g = run("gather", warm=False, steps=1)
    assert t_m == t_g, "fused guided must match the host FSM path"
    assert s_m.flight.fused_sampled_windows_total > 0  # guided rides sampled epilogue
    assert s_m.flight.compiles_after_warmup_total == 0, (
        f"post-warmup compiles: {s_m.flight.post_warmup_keys}"
    )


# ---------------------------------------------------------------------------
# Fused speculative window
# ---------------------------------------------------------------------------


def _cache_rows(cache, tables, upto):
    """Gather per-position KV rows [B, upto, KVH, HD] (layer-stacked) from a
    paged cache given each row's block table and confirmed length."""
    L, N, BS = cache.shape[0], cache.shape[1], cache.shape[2]
    out = []
    for b in range(tables.shape[0]):
        rows = []
        for p in range(upto[b]):
            blk = int(tables[b, p // BS])
            rows.append(np.asarray(cache[:, blk, p % BS]))
        out.append(np.stack(rows, axis=1))  # [L, upto, KVH, HD]
    return out


@pytest.mark.slow  # interpret-mode Pallas e2e; the CI `fused-sampling`
# job gates the same invariants through bench.py in its own budget
def test_fused_spec_window_mixed_accept_kv_parity():
    """One fused spec launch (draft != target => real rejections): the
    host-replay contract reconstructs the confirmed token stream, SOME
    rounds accept and SOME reject (mixed coverage), and the target cache's
    confirmed KV rows are bit-for-bit what a clean prefill of that exact
    stream writes — i.e. rejection costs no rewind and leaves no stale
    confirmed state."""
    R, gamma, B, P = 3, 2, 2, 12
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    # Draft = target + tiny perturbation: a random-init tiny model's
    # argmax is noise-sensitive, so 0.002 is already enough for rows to
    # disagree — some proposals accept, some reject (both asserted).
    noise = jax.random.PRNGKey(42)
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(noise, len(leaves))
    draft = jax.tree_util.tree_unflatten(
        treedef,
        [l + 0.002 * jax.random.normal(k, l.shape, l.dtype)
         for l, k in zip(leaves, keys)],
    )

    rng = np.random.default_rng(13)
    prompts = [rng.integers(1, 255, size=P) for _ in range(B)]
    tables = np.stack(
        [np.arange(1 + 2 * b, 3 + 2 * b, dtype=np.int32) for b in range(B)]
    )
    t_j = jnp.asarray(tables)

    k_t, v_t = _fresh(CFG, num_blocks=2 * B + 2)
    k_d, v_d = _fresh(CFG, num_blocks=2 * B + 2)
    for b in range(B):
        _, k_t, v_t = _prefill(params, CFG, k_t, v_t, prompts[b], t_j[b])
        _, k_d, v_d = _prefill(draft, CFG, k_d, v_d, prompts[b], t_j[b])

    t0 = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    xprev = jnp.asarray([int(p[-1]) for p in prompts], jnp.int32)
    pos = jnp.full((B,), P, jnp.int32)
    active = jnp.ones((B,), bool)
    greedy = (jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
              jnp.ones((B,), jnp.float32))
    unif = jnp.full((R, B, 2 * gamma + 1), 0.25, jnp.float32)

    n0 = mk.trace_launch_count()
    toks_out, accepted, k_t, v_t, k_d, v_d = llama.decode_spec_fused(
        params, MEGA, draft, MEGA, k_t, v_t, k_d, v_d,
        t0, xprev, pos, t_j, t_j, active, *greedy, unif,
        rounds=R, gamma=gamma,
    )
    assert mk.trace_launch_count() - n0 == 1, "spec window must be ONE launch"

    acc = np.asarray(accepted)  # [R, B]
    toks_h = np.asarray(toks_out)  # [R, B, gamma+1]
    assert acc.min() >= 0 and acc.max() <= gamma
    assert acc.max() > 0, "perturbed draft should still land some proposals"
    assert acc.min() < gamma, "perturbed draft should also get rejected"

    # Host-replay contract: per round, k accepted proposals then the
    # verifier's bonus/fallback token; cursor advances k+1.
    streams, upto = [], []
    for b in range(B):
        conf = list(prompts[b]) + [int(t0[b])]
        for r in range(R):
            kk = int(acc[r, b])
            conf += [int(t) for t in toks_h[r, b, :kk]] + [int(toks_h[r, b, gamma])]
        streams.append(conf)
        upto.append(len(conf) - 1)  # last token's KV is the next input, unwritten

    # Gold: clean prefill of each confirmed stream (same math, no spec).
    k_g, v_g = _fresh(CFG, num_blocks=2 * B + 2)
    for b in range(B):
        _, k_g, v_g = _prefill(params, CFG, k_g, v_g, streams[b][:-1], t_j[b])

    got_k = _cache_rows(k_t, tables, upto)
    got_v = _cache_rows(v_t, tables, upto)
    want_k = _cache_rows(k_g, tables, upto)
    want_v = _cache_rows(v_g, tables, upto)
    for b in range(B):
        np.testing.assert_allclose(got_k[b], want_k[b], atol=2e-4)
        np.testing.assert_allclose(got_v[b], want_v[b], atol=2e-4)


@pytest.mark.slow  # interpret-mode Pallas e2e; the CI `fused-sampling`
# job gates the same invariants through bench.py in its own budget
def test_scheduler_spec_fused_e2e():
    """Scheduler spec path rides the fused spec window (draft attached,
    gate engaged): greedy token parity with a plain gather scheduler, the
    spec-fused counters advance, >= 2 accepted tokens/round on the
    draft==target smoke config, and zero post-warmup compiles across the
    enlarged key space (fused greedy + sampled + spec executables warmed)."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(impl, draft, warm, steps):
        sched = Scheduler(CFG.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=steps, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        if draft:
            sched.attach_draft(CFG, params, gamma=2)
        if warm:
            sched.warmup(ctx_tokens=64)
            sched.flight.mark_warmup_done(warmed=True)
        toks = {}
        for i in range(3):
            sched.add_request(f"s{i}", list(range(1 + i, 25 + i)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=12, ignore_eos=True))
        for _ in range(300):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return sched, toks

    s_f, t_f = run("megakernel", draft=True, warm=True, steps=8)
    assert s_f._use_fused_spec, "fused spec gate must engage on the tiny config"
    s_g, t_g = run("gather", draft=False, warm=False, steps=1)
    assert t_f == t_g, "fused spec must emit identical greedy tokens"
    assert s_f.flight.spec_fused_windows_total > 0
    assert s_f.flight.spec_fused_accepted_tokens_total > 0
    assert s_f.flight.compiles_after_warmup_total == 0, (
        f"post-warmup compiles: {s_f.flight.post_warmup_keys}"
    )
    st = s_f.spec_stats.to_dict()
    assert st["accepted_per_round"] >= 2.0, st
    stats = s_f.flight.to_stats()
    assert stats["spec_fused_windows_total"] == s_f.flight.spec_fused_windows_total
