"""Ragged paged-attention megakernel: interpreter-mode parity vs the XLA
gather path over head layouts (GQA/MQA/MHA), ragged edge cases (length-1
decode rows mixed with chunk rows, short sequences in wide buckets, page-
boundary prefix lengths, dead scratch-block-0 slots), the int8-KV
dequant-in-VMEM path, and the fused N-step decode window (token AND KV
cache-content parity vs ``decode_multi``, exactly ONE pallas launch per
window, 0 post-warmup compiles at the scheduler).

Everything runs the Pallas interpreter on CPU (tier-1 CI); the kernels are
the same code the TPU auto-selection dispatches.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine.attention import megakernel as mk
from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

CFG = get_config("tiny")  # GQA: 4 heads over 2 KV heads
MEGA = CFG.replace(attention_impl="megakernel")


def _fresh(cfg, num_blocks=64):
    c = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.float32)
    return c.k, c.v


def _prefill(params, cfg, k, v, toks, table, cache_len=0):
    t = jnp.asarray(np.asarray(toks, np.int32))
    return jax.jit(
        lambda p, k, v: llama.prefill(
            p, cfg, k, v, t, jnp.int32(len(toks)), jnp.int32(cache_len), table
        )
    )(params, k, v)


# ---------------------------------------------------------------------------
# Head layouts: GQA / MHA / MQA
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kvh", [2, 4, 1], ids=["gqa", "mha", "mqa"]
)
def test_decode_parity_head_layouts(kvh):
    """Megakernel decode logits + written KV match the XLA gather for every
    head layout the block-diagonal GQA fold must cover."""
    base = CFG.replace(num_kv_heads=kvh)
    params = llama.init_params(base, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(1)
    table = jnp.asarray(np.arange(1, 5, dtype=np.int32))
    toks = rng.integers(1, 255, size=30)

    B = 3
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 30, jnp.int32)
    tables_d = jnp.asarray(np.tile(np.arange(1, 5, dtype=np.int32), (B, 1)))
    active = jnp.ones((B,), bool)

    def run(cfg):
        k, v = _fresh(cfg)
        _, k, v = _prefill(params, cfg, k, v, toks, table)
        return jax.jit(
            lambda p, k, v: llama.decode(p, cfg, k, v, dtoks, pos, tables_d, active)
        )(params, k, v)

    lg_g, kg, vg = run(base)
    lg_m, km, vm = run(base.replace(attention_impl="megakernel"))
    np.testing.assert_allclose(np.asarray(lg_g), np.asarray(lg_m), atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg), np.asarray(km), atol=2e-5)
    np.testing.assert_allclose(np.asarray(vg), np.asarray(vm), atol=2e-5)


# ---------------------------------------------------------------------------
# Ragged edge cases
# ---------------------------------------------------------------------------


def test_prefill_chunk_with_prefix_parity():
    """A (start, len) chunk row over a cached prefix — including a chunk
    that starts exactly ON a page boundary — matches the gather path."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(2)
    table = jnp.asarray(np.arange(1, 6, dtype=np.int32))
    first = rng.integers(1, 255, size=32)  # ends exactly at 2 pages (bs=16)
    second = rng.integers(1, 255, size=19)

    def run(cfg):
        k, v = _fresh(cfg)
        lg1, k, v = _prefill(params, cfg, k, v, first, table)
        lg2, k, v = _prefill(params, cfg, k, v, second, table, cache_len=32)
        return lg1, lg2, k, v

    g1, g2, kg, vg = run(CFG)
    m1, m2, km, vm = run(MEGA)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(m1), atol=2e-4)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(m2), atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg), np.asarray(km), atol=2e-5)


def test_mixed_step_parity_chunk_plus_decode_rows():
    """The whole mixed step — a ragged chunk row AND length-1 decode rows in
    one launch — matches the two-shape XLA path, including padded chunk
    queries (len < bucket) and an INACTIVE decode lane. Scratch block 0 is
    excluded from the KV comparison: dead rows sink different garbage
    there by design and it is never handed out or read."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(3)
    toks = rng.integers(1, 255, size=21)  # short seq: 21 tokens in 2 pages
    p_table = jnp.asarray(np.array([5, 6, 7, 8], np.int32))

    B = 4  # 3 live decode rows + 1 dead lane
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    dpos = jnp.asarray(np.array([30, 16, 7, 0], np.int32))  # incl. page-exact 16
    # Wide bucket for a short row: row 2 (7 tokens) rides an 8-wide table.
    tables_d = jnp.asarray(
        np.stack([np.r_[1:5, 0, 0, 0, 0], np.r_[9:13, 0, 0, 0, 0],
                  np.r_[13:17, 0, 0, 0, 0], np.zeros(8, np.int64)]).astype(np.int32)
    )
    active = jnp.asarray(np.array([True, True, True, False]))

    chunk = np.zeros((16,), np.int32)
    chunk[:9] = rng.integers(1, 255, size=9)

    # Fixed prompts so both impls seed bit-identical caches. The chunk
    # sequence's 21-token cached prefix (toks above) lives at blocks 5-8.
    seed_prompts = [
        (toks, np.arange(5, 9)),
        (rng.integers(1, 255, size=30), np.arange(1, 5)),
        (rng.integers(1, 255, size=16), np.arange(9, 13)),
        (rng.integers(1, 255, size=7), np.arange(13, 17)),
    ]

    def run(cfg):
        k, v = _fresh(cfg)
        for toks_s, tbl in seed_prompts:
            _, k, v = _prefill(params, cfg, k, v, toks_s,
                               jnp.asarray(tbl.astype(np.int32)))
        return jax.jit(
            lambda p, k, v: llama.mixed_step(
                p, cfg, k, v, jnp.asarray(chunk), jnp.int32(9), jnp.int32(21),
                p_table, dtoks, dpos, tables_d, active,
            )
        )(params, k, v)

    lg_g, kg, vg = run(CFG)
    lg_m, km, vm = run(MEGA)
    # Live rows only: logits row 0 is the chunk, rows 1..3 the live decode
    # lanes. The dead lane's logits are garbage in BOTH impls (masked
    # softmax junk vs kernel zeros) and the scheduler never reads them.
    np.testing.assert_allclose(np.asarray(lg_g)[:4], np.asarray(lg_m)[:4], atol=2e-4)
    np.testing.assert_allclose(np.asarray(kg)[:, 1:], np.asarray(km)[:, 1:], atol=2e-5)
    np.testing.assert_allclose(np.asarray(vg)[:, 1:], np.asarray(vm)[:, 1:], atol=2e-5)


def test_dead_queries_return_zeros():
    """Dead ragged rows (meta active=0) read nothing and return exact zeros
    from the kernel — the pl.when skip, not masked softmax garbage."""
    kvh, hd, bs = 2, 16, 16
    H = 4
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((3, H, hd)).astype(np.float32))
    ke = jnp.asarray(rng.standard_normal((3, kvh, hd)).astype(np.float32))
    k_pages = jnp.asarray(rng.standard_normal((6, bs, kvh, hd)).astype(np.float32))
    v_pages = jnp.asarray(rng.standard_normal((6, bs, kvh, hd)).astype(np.float32))
    tables = jnp.asarray(np.array([[1, 2], [3, 4], [0, 0]], np.int32))
    meta = mk.build_meta(
        jnp.asarray(np.array([0, 1, 2], np.int32)),
        jnp.asarray(np.array([20, 20, 0], np.int32)),
        jnp.asarray(np.array([0, 1, 2], np.int32)),
        jnp.asarray(np.array([1, 2, 2], np.int32)),  # row 2: no fresh keys either
        jnp.asarray(np.array([1, 1, 0], np.int32)),  # row 2 dead
    )
    out = mk.ragged_paged_attention(
        q, ke, ke, k_pages, v_pages, tables, meta,
        num_kv_heads=kvh, block_size=bs, interpret=True,
    )
    assert np.all(np.asarray(out)[2] == 0.0), "dead query must return zeros"
    assert np.all(np.isfinite(np.asarray(out)[:2]))


# ---------------------------------------------------------------------------
# int8 KV: dequant-in-VMEM path
# ---------------------------------------------------------------------------


def test_int8_kv_megakernel_parity():
    """Megakernel attention over a QuantKv cache (int8 codes + per-(token,
    head) scales dequantized in VMEM) matches the gather path reading the
    SAME quantized cache — bitwise-equal inputs, so tolerance is float
    accumulation, not quantization error."""
    cfg8_g = CFG.replace(kv_cache_dtype="int8")
    cfg8_m = cfg8_g.replace(attention_impl="megakernel")
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(5)
    table = jnp.asarray(np.arange(1, 5, dtype=np.int32))
    toks = rng.integers(1, 255, size=30)

    B = 2
    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 30, jnp.int32)
    tables_d = jnp.asarray(np.tile(np.arange(1, 5, dtype=np.int32), (B, 1)))
    active = jnp.ones((B,), bool)

    def run(cfg):
        k, v = _fresh(cfg)
        _, k, v = _prefill(params, cfg, k, v, toks, table)
        lg, k, v = jax.jit(
            lambda p, k, v: llama.decode(p, cfg, k, v, dtoks, pos, tables_d, active)
        )(params, k, v)
        return lg

    lg_g = run(cfg8_g)
    lg_m = run(cfg8_m)
    np.testing.assert_allclose(np.asarray(lg_g), np.asarray(lg_m), atol=5e-4)


def test_paged_int8_degrades_to_gather():
    """attention_impl='paged' + int8 KV no longer raises at config
    validation; the engine degrades to the gather with a warning."""
    cfg = CFG.replace(attention_impl="paged", kv_cache_dtype="int8")  # no raise
    cache = KvCacheArrays.create(cfg, num_blocks=8, dtype=jnp.float32)
    assert llama.resolve_attention_impl(cfg, cache.k) == "gather"
    # megakernel keeps the fused path for int8.
    cfg_m = CFG.replace(attention_impl="megakernel", kv_cache_dtype="int8")
    assert llama.resolve_attention_impl(cfg_m, cache.k) == "megakernel"


def test_attention_impl_validation():
    with pytest.raises(ValueError, match="attention_impl"):
        CFG.replace(attention_impl="bogus")
    for ok in ("auto", "gather", "paged", "megakernel"):
        assert CFG.replace(attention_impl=ok).attention_impl == ok


# ---------------------------------------------------------------------------
# Fused N-step decode window
# ---------------------------------------------------------------------------


def test_fused_window_parity_and_single_launch():
    """One fused launch serves an entire greedy decode window: tokens AND
    written KV cache contents match greedy ``decode_multi``, and the traced
    executable contains exactly ONE pallas_call site."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(6)
    B, steps = 3, 4
    toks = rng.integers(1, 255, size=21)
    tables = np.stack([np.arange(1 + 4 * b, 5 + 4 * b, dtype=np.int32) for b in range(B)])

    k, v = _fresh(CFG)
    for b in range(B):
        _, k, v = _prefill(params, CFG, k, v, toks, jnp.asarray(tables[b]))

    dtoks = jnp.asarray(rng.integers(1, 255, size=B).astype(np.int32))
    pos = jnp.full((B,), 21, jnp.int32)
    active = jnp.ones((B,), bool)
    t_j = jnp.asarray(tables)

    n0 = mk.trace_launch_count()
    toks_f, kf, vf = llama.decode_multi_fused(
        params, MEGA, k, v, dtoks, pos, t_j, active, num_steps=steps
    )
    assert mk.trace_launch_count() - n0 == 1, "fused window must be ONE launch"

    greedy = (jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
              jnp.ones((B,), jnp.float32))
    toks_r, kr, vr = jax.jit(
        lambda p, k, v: llama.decode_multi(
            p, CFG, k, v, dtoks, pos, t_j, active, *greedy,
            jax.random.PRNGKey(9), steps,
        )
    )(params, k, v)
    np.testing.assert_array_equal(np.asarray(toks_f), np.asarray(toks_r))
    np.testing.assert_allclose(
        np.asarray(kf)[:, 1:], np.asarray(kr)[:, 1:], atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(vf)[:, 1:], np.asarray(vr)[:, 1:], atol=2e-4
    )


def test_scheduler_fused_window_e2e():
    """Scheduler end-to-end with attention_impl='megakernel': greedy token
    streams match the gather scheduler, every decode window dispatches as
    ONE pallas launch (flight-recorder gauge == 1), and a warmed scheduler
    compiles NOTHING mid-traffic."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

    def run(impl, warm):
        sched = Scheduler(CFG.replace(attention_impl=impl), params, SchedulerConfig(
            num_blocks=128, max_running=4,
            prefill_buckets=[32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=8, enable_prefix_caching=False,
            enable_overlap_decode=False, enable_mixed_batching=False,
        ), dtype=jnp.float32)
        if warm:
            sched.warmup(ctx_tokens=64)
            sched.flight.mark_warmup_done(warmed=True)
        toks = {}
        for i in range(3):
            sched.add_request(f"r{i}", list(range(1 + i, 25 + i)),
                              SamplingParams(temperature=0.0),
                              StopConditions(max_tokens=18, ignore_eos=True))
        for _ in range(200):
            if not sched.has_work():
                break
            for s, o in sched.step():
                if o.token_id >= 0:
                    toks.setdefault(s.request_id, []).append(o.token_id)
        return sched, toks

    s_m, t_m = run("megakernel", warm=True)
    s_g, t_g = run("gather", warm=False)
    assert t_m == t_g, "megakernel scheduler must emit identical greedy tokens"
    assert s_m._use_fused_window
    assert s_m.flight.fused_windows_total > 0
    assert s_m.flight.fused_window_pallas_launches == 1
    assert s_m.flight.compiles_after_warmup_total == 0, (
        f"post-warmup compiles: {s_m.flight.post_warmup_keys}"
    )
    stats = s_m.flight.to_stats()
    assert stats["fused_window_pallas_launches"] == 1
    assert stats["fused_windows_total"] == s_m.flight.fused_windows_total


# ---------------------------------------------------------------------------
# Flight recorder: paged-path cost model + mixed-step phase split
# ---------------------------------------------------------------------------


def test_cost_model_paged_vs_gather_bytes():
    from dynamo_tpu.engine.flight_recorder import StepCostModel

    gather = StepCostModel(1000, 2000, 10.0, peak_flops=1e12, peak_bw=1e11,
                           kv_read_factor=3.0)
    paged = StepCostModel(1000, 2000, 10.0, peak_flops=1e12, peak_bw=1e11,
                          kv_read_factor=1.0)
    fg, bg = gather.step_cost(4, 100)
    fp, bp = paged.step_cost(4, 100)
    assert fg == fp  # FLOPs don't depend on the attention path
    # gather: 2000 + 3*100*10 + 4*10; paged: 2000 + 100*10 + 4*10
    assert bg - bp == pytest.approx(2 * 100 * 10.0)
    # A decode_multi window streams params once per step; the fused window
    # streams them once per window.
    _, b_loop = paged.step_cost(32, 800, param_passes=8.0)
    _, b_fused = paged.step_cost(32, 100, param_passes=1.0)
    assert b_loop - b_fused == pytest.approx(7 * 2000 + 700 * 10.0)


def test_mixed_step_phase_split():
    """record_mixed_step books the chunk into the prefill roofline and the
    decode rows into decode — both gauges move, and the mixed histogram
    still counts the step."""
    from dynamo_tpu.engine.flight_recorder import FlightRecorder, StepCostModel

    fr = FlightRecorder()
    fr.set_cost_model(StepCostModel(10_000, 20_000, 64.0,
                                    peak_flops=1e12, peak_bw=1e11))
    fr.record_mixed_step(0.01, prefill_tokens=128, decode_tokens=8,
                         kv_read_prefill=256, kv_read_decode=4096)
    util = fr.utilization()
    assert util["prefill"][0] > 0 and util["decode"][1] > 0
    assert "mixed" not in util  # cost split entirely into the real phases
    stats = fr.to_stats()
    assert stats["step_mixed_steps_total"] == 1
    assert stats["step_mixed_tokens_total"] == 136
    assert stats["step_prefill_flops_total"] > 0
    assert stats["step_decode_bytes_total"] > 0
