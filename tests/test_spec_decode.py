"""Speculative decoding: output must equal plain greedy target decoding;
acceptance accounting sanity."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.spec_decode import SpecDecoder, SpecDecodeStats

CFG = get_config("tiny")


def greedy_reference(params, prompt, max_tokens):
    """Plain greedy decode, same paged-cache machinery."""
    bs = CFG.block_size
    n_blocks = (len(prompt) + max_tokens + bs - 1) // bs + 1
    table = jnp.arange(1, 1 + n_blocks, dtype=jnp.int32)
    cache = KvCacheArrays.create(CFG, n_blocks + 1, dtype=jnp.float32)
    T = len(prompt)
    bucket = 32 if T <= 32 else 64
    padded = jnp.zeros((bucket,), dtype=jnp.int32).at[:T].set(jnp.asarray(prompt, dtype=jnp.int32))
    logits, k, v = llama.prefill(params, CFG, cache.k, cache.v, padded, jnp.int32(T), jnp.int32(0), table)
    out = [int(jnp.argmax(logits))]
    pos = T
    while len(out) < max_tokens:
        logits, k, v = llama.decode(
            params, CFG, k, v,
            jnp.asarray([out[-1]], dtype=jnp.int32),
            jnp.asarray([pos], dtype=jnp.int32),
            table[None, :],
            jnp.ones((1,), dtype=bool),
        )
        out.append(int(jnp.argmax(logits[0])))
        pos += 1
    return out


def test_spec_matches_greedy_distinct_draft():
    """Different draft weights: lossless greedy spec decode — output
    identical to target-only decoding regardless of draft quality."""
    tp = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    dp = llama.init_params(CFG, jax.random.PRNGKey(7), dtype=jnp.float32)
    prompt = list(range(40, 60))
    ref = greedy_reference(tp, prompt, 12)
    stats = SpecDecodeStats()
    dec = SpecDecoder(CFG, tp, CFG, dp, gamma=3, dtype=jnp.float32)
    out = dec.generate(prompt, 12, stats=stats)
    assert out == ref
    assert stats.num_rounds > 0
    assert stats.num_draft_tokens == stats.num_rounds * 3


def test_spec_perfect_draft_accepts_everything():
    """Draft == target → every proposal accepted, rate 1.0."""
    tp = llama.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    prompt = list(range(10, 26))
    ref = greedy_reference(tp, prompt, 10)
    stats = SpecDecodeStats()
    dec = SpecDecoder(CFG, tp, CFG, tp, gamma=4, dtype=jnp.float32)
    out = dec.generate(prompt, 10, stats=stats)
    assert out == ref
    assert stats.acceptance_rate == 1.0
    # γ+1 tokens per round: far fewer rounds than tokens.
    assert stats.num_rounds <= (10 // 5) + 1


def test_spec_stats_dict():
    s = SpecDecodeStats(num_spec_tokens=8, num_accepted_tokens=6, num_draft_tokens=8, num_rounds=2)
    d = s.to_dict()
    assert d["acceptance_rate"] == 0.75


def test_spec_stats_zero_round_guards():
    """A fresh (zero-round) history yields 0.0 everywhere — never NaN/ZeroDiv
    — and to_dict round-trips the guarded values."""
    s = SpecDecodeStats()
    assert s.acceptance_rate == 0.0
    assert s.accepted_per_round == 0.0
    d = s.to_dict()
    assert d["acceptance_rate"] == 0.0
    assert d["accepted_per_round"] == 0.0
    assert d["accepted_per_position"] == []


def test_spec_stats_gamma_zero_rounds():
    """γ=0 rounds propose nothing: acceptance_rate stays 0.0 (no draft
    tokens to divide by) but accepted_per_round still counts the bonus
    token every round emits."""
    s = SpecDecodeStats()
    s.record_round(0, 0)
    s.record_round(0, 0)
    assert s.num_draft_tokens == 0
    assert s.acceptance_rate == 0.0
    assert s.accepted_per_round == 1.0  # bonus/correction token per round
    assert np.isfinite(s.to_dict()["accepted_per_round"])


def test_spec_stats_all_rejected():
    """Every proposal rejected: rate 0.0, but each round still confirms the
    verifier's correction token, so accepted_per_round == 1.0 (the fused
    window's worst case is target-only speed, not zero progress)."""
    s = SpecDecodeStats()
    for _ in range(4):
        s.record_round(0, 3)
    assert s.num_draft_tokens == 12
    assert s.acceptance_rate == 0.0
    assert s.accepted_per_round == 1.0
    assert s.accepted_per_position == [0, 0, 0]


def test_spec_stats_accepted_per_round_mixed():
    """Mixed accept counts across rows/rounds: (accepted + rounds) / rounds
    — e.g. k=3,1,2 over 3 row-rounds confirms (6+3)/3 = 3 tokens/round."""
    s = SpecDecodeStats()
    for k in (3, 1, 2):
        s.record_round(k, 3)
    assert s.accepted_per_round == 3.0
    assert s.to_dict()["accepted_per_round"] == 3.0
