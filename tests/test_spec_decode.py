"""Speculative decoding: output must equal plain greedy target decoding;
acceptance accounting sanity."""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.spec_decode import SpecDecoder, SpecDecodeStats

CFG = get_config("tiny")


def greedy_reference(params, prompt, max_tokens):
    """Plain greedy decode, same paged-cache machinery."""
    bs = CFG.block_size
    n_blocks = (len(prompt) + max_tokens + bs - 1) // bs + 1
    table = jnp.arange(1, 1 + n_blocks, dtype=jnp.int32)
    cache = KvCacheArrays.create(CFG, n_blocks + 1, dtype=jnp.float32)
    T = len(prompt)
    bucket = 32 if T <= 32 else 64
    padded = jnp.zeros((bucket,), dtype=jnp.int32).at[:T].set(jnp.asarray(prompt, dtype=jnp.int32))
    logits, k, v = llama.prefill(params, CFG, cache.k, cache.v, padded, jnp.int32(T), jnp.int32(0), table)
    out = [int(jnp.argmax(logits))]
    pos = T
    while len(out) < max_tokens:
        logits, k, v = llama.decode(
            params, CFG, k, v,
            jnp.asarray([out[-1]], dtype=jnp.int32),
            jnp.asarray([pos], dtype=jnp.int32),
            table[None, :],
            jnp.ones((1,), dtype=bool),
        )
        out.append(int(jnp.argmax(logits[0])))
        pos += 1
    return out


def test_spec_matches_greedy_distinct_draft():
    """Different draft weights: lossless greedy spec decode — output
    identical to target-only decoding regardless of draft quality."""
    tp = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    dp = llama.init_params(CFG, jax.random.PRNGKey(7), dtype=jnp.float32)
    prompt = list(range(40, 60))
    ref = greedy_reference(tp, prompt, 12)
    stats = SpecDecodeStats()
    dec = SpecDecoder(CFG, tp, CFG, dp, gamma=3, dtype=jnp.float32)
    out = dec.generate(prompt, 12, stats=stats)
    assert out == ref
    assert stats.num_rounds > 0
    assert stats.num_draft_tokens == stats.num_rounds * 3


def test_spec_perfect_draft_accepts_everything():
    """Draft == target → every proposal accepted, rate 1.0."""
    tp = llama.init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)
    prompt = list(range(10, 26))
    ref = greedy_reference(tp, prompt, 10)
    stats = SpecDecodeStats()
    dec = SpecDecoder(CFG, tp, CFG, tp, gamma=4, dtype=jnp.float32)
    out = dec.generate(prompt, 10, stats=stats)
    assert out == ref
    assert stats.acceptance_rate == 1.0
    # γ+1 tokens per round: far fewer rounds than tokens.
    assert stats.num_rounds <= (10 // 5) + 1


def test_spec_stats_dict():
    s = SpecDecodeStats(num_spec_tokens=8, num_accepted_tokens=6, num_draft_tokens=8, num_rounds=2)
    d = s.to_dict()
    assert d["acceptance_rate"] == 0.75
