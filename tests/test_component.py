"""Integration tests for the component model: serve → discover → route →
stream, plus cancellation and worker-death handling. These exercise the real
TCP call-home data plane even though the control plane is in-memory (mirrors
the reference's mocker-based distributed tests, SURVEY.md §4)."""

import asyncio

import pytest

from dynamo_tpu.runtime import (
    Context,
    DistributedRuntime,
    PushRouter,
    RouterMode,
)
from dynamo_tpu.runtime.engine import StreamDisconnect


async def _echo_handler(request, context):
    for i in range(int(request.get("n", 3))):
        yield {"i": i, "msg": request.get("msg", "")}


async def make_drt():
    return await DistributedRuntime.detached()


async def test_serve_and_roundtrip_local_fast_path():
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")
        await ep.serve_endpoint(_echo_handler)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)
        out = [a.data async for a in router.generate({"n": 3, "msg": "hi"})]
        assert out == [{"i": 0, "msg": "hi"}, {"i": 1, "msg": "hi"}, {"i": 2, "msg": "hi"}]
    finally:
        await drt.shutdown()


async def test_remote_wire_path():
    """Force the network path by removing the local-engine registry entry:
    requests go over pub/sub and responses over the TCP call-home plane."""
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")
        handle = await ep.serve_endpoint(_echo_handler)
        drt.local_engines.pop(handle.instance.instance_id)  # simulate remote worker
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)
        out = [a.data async for a in router.generate({"n": 4, "msg": "wire"})]
        assert [o["i"] for o in out] == [0, 1, 2, 3]
    finally:
        await drt.shutdown()


async def test_round_robin_across_instances():
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")

        def make_handler(tag):
            async def handler(request, context):
                yield {"worker": tag}

            return handler

        await ep.serve_endpoint(make_handler("a"))
        await ep.serve_endpoint(make_handler("b"))
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)
        router = PushRouter(client, RouterMode.ROUND_ROBIN)
        seen = []
        for _ in range(4):
            async for a in router.generate({}):
                seen.append(a.data["worker"])
        assert sorted(set(seen)) == ["a", "b"]
        assert seen[:2] != seen[2:4] or seen[0] != seen[1]  # alternates
    finally:
        await drt.shutdown()


async def test_instance_removed_on_lease_loss():
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")
        handle = await ep.serve_endpoint(_echo_handler, lease_ttl_s=0.5)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        # Worker dies: revoke its lease directly (keepalive task can't help).
        await drt.store.revoke_lease(handle.lease.id)
        for _ in range(50):
            if not client.instances:
                break
            await asyncio.sleep(0.05)
        assert not client.instances
    finally:
        await drt.shutdown()


async def test_cancellation_stops_inflight_request_gracefully():
    """stop_generating → graceful 'cancel' op: the handler observes the
    stopped context, finishes cleanly, and the client stream simply ends."""
    drt = await make_drt()
    started = asyncio.Event()
    progressed = []
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")

        async def slow_handler(request, context):
            started.set()
            for i in range(1000):
                if context.is_stopped():
                    return
                progressed.append(i)
                yield {"i": i}
                await asyncio.sleep(0.01)

        handle = await ep.serve_endpoint(slow_handler)
        drt.local_engines.pop(handle.instance.instance_id)  # use wire path
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)

        ctx = Context()
        got = []
        async for a in router.generate({}, ctx):
            got.append(a.data)
            if len(got) == 3:
                ctx.stop_generating()
        assert len(progressed) < 1000
    finally:
        await drt.shutdown()


async def test_kill_abandons_inflight_request():
    """kill → hard 'kill' op: the worker-side handler breaks mid-stream and
    the client sees the cancellation error."""
    drt = await make_drt()
    progressed = []
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")

        async def oblivious_handler(request, context):
            # Ignores the context entirely: only the hard kill can stop it.
            for i in range(1000):
                progressed.append(i)
                yield {"i": i}
                await asyncio.sleep(0.01)

        handle = await ep.serve_endpoint(oblivious_handler)
        drt.local_engines.pop(handle.instance.instance_id)  # use wire path
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)

        ctx = Context()
        got = []
        with pytest.raises(RuntimeError):
            async for a in router.generate({}, ctx):
                got.append(a.data)
                if len(got) == 3:
                    ctx.kill()
        assert len(progressed) < 1000
    finally:
        await drt.shutdown()


async def test_stream_disconnect_surfaces_for_migration():
    """A worker that dies mid-stream must surface StreamDisconnect so the
    Migration operator can replay (ref: migration.rs)."""
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")

        async def dying_handler(request, context):
            yield {"i": 0}
            raise ConnectionResetError("worker crash")  # simulates abrupt death

        handle = await ep.serve_endpoint(dying_handler)
        drt.local_engines.pop(handle.instance.instance_id)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        router = PushRouter(client)
        got = []
        with pytest.raises(StreamDisconnect):
            async for a in router.generate({}):
                got.append(a.data)
        assert got == [{"i": 0}]
    finally:
        await drt.shutdown()


async def test_stats_scrape():
    drt = await make_drt()
    try:
        ep = drt.namespace("test").component("comp").endpoint("gen")
        await ep.serve_endpoint(_echo_handler, stats_handler=lambda: {"kv_usage": 0.5})
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        stats = await client.scrape_stats()
        assert len(stats) == 1
        (s,) = stats.values()
        assert s["kv_usage"] == 0.5 and s["in_flight"] == 0
    finally:
        await drt.shutdown()
