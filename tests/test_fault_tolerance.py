"""Fault-tolerance e2e (ref: tests/fault_tolerance/test_request_migration.py):
a worker dies mid-stream; the Migration operator replays the
prefix-completed request on another instance and the client sees one
uninterrupted stream. Also: cancellation propagation (ref:
test_request_cancellation.py)."""

import asyncio

import pytest

from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.entrypoint import RouterEngine
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context, StreamDisconnect
from dynamo_tpu.runtime.push_router import PushRouter


class FlakyEngine:
    """Emits deterministic tokens; crashes abruptly after N tokens, once."""

    def __init__(self, crash_after=3):
        self.crash_after = crash_after
        self.crashed = False
        self.calls = 0

    async def generate(self, request, context):
        self.calls += 1
        start = len(request["token_ids"])
        max_tokens = request["stop_conditions"]["max_tokens"]
        for i in range(max_tokens):
            if not self.crashed and i >= self.crash_after:
                self.crashed = True
                raise ConnectionResetError("worker killed")  # abrupt death
            tok = start + i  # deterministic continuation: token = position
            finish = "length" if i == max_tokens - 1 else None
            yield {"token_ids": [tok], "finish_reason": finish, "index": 0}
            await asyncio.sleep(0.001)


class SteadyEngine:
    async def generate(self, request, context):
        start = len(request["token_ids"])
        max_tokens = request["stop_conditions"]["max_tokens"]
        for i in range(max_tokens):
            finish = "length" if i == max_tokens - 1 else None
            yield {"token_ids": [start + i], "finish_reason": finish, "index": 0}
            await asyncio.sleep(0.001)


async def serve_wire(drt, ep, engine):
    handle = await ep.serve_endpoint(engine.generate)
    drt.local_engines.pop(handle.instance.instance_id)
    return handle


async def test_migration_replays_on_stream_drop(caplog):
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("ft").component("w").endpoint("gen")
        flaky = FlakyEngine(crash_after=3)
        steady = SteadyEngine()
        h1 = await serve_wire(drt, ep, flaky)
        h2 = await serve_wire(drt, ep, steady)
        client = await ep.client()
        await client.wait_for_instances(2, timeout=5)

        router = PushRouter(client)
        engine = Migration(migration_limit=2).attach(RouterEngine(router))

        prompt = list(range(10))
        request = {"token_ids": prompt, "sampling_options": {}, "stop_conditions": {"max_tokens": 8}}

        # Route until we hit the flaky worker first (router is round-robin;
        # try twice to cover either ordering).
        for _ in range(2):
            got = []
            async for item in engine.generate(dict(request), Context()):
                data = item.data if hasattr(item, "data") else item
                if data and data.get("token_ids"):
                    got.extend(data["token_ids"])
            assert len(got) == 8
            # Deterministic continuation: each token = current sequence
            # length, so a migrated stream yields exactly this.
            assert got == list(range(10, 18))
            if flaky.crashed:
                break
        assert flaky.crashed, "flaky worker should have been hit"
        assert steady is not None
    finally:
        await drt.shutdown()


async def test_migration_limit_zero_surfaces_disconnect():
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("ft2").component("w").endpoint("gen")
        flaky = FlakyEngine(crash_after=1)
        await serve_wire(drt, ep, flaky)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        engine = Migration(migration_limit=0).attach(RouterEngine(PushRouter(client)))
        request = {"token_ids": [1, 2], "sampling_options": {}, "stop_conditions": {"max_tokens": 5}}
        with pytest.raises(StreamDisconnect):
            async for _ in engine.generate(request, Context()):
                pass
    finally:
        await drt.shutdown()


async def test_migration_exhausted_after_repeated_crashes():
    drt = await DistributedRuntime.detached()
    try:
        ep = drt.namespace("ft3").component("w").endpoint("gen")

        class AlwaysCrash:
            async def generate(self, request, context):
                yield {"token_ids": [1], "finish_reason": None, "index": 0}
                raise ConnectionResetError("dead again")

        await serve_wire(drt, ep, AlwaysCrash())
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        engine = Migration(migration_limit=2).attach(RouterEngine(PushRouter(client)))
        request = {"token_ids": [1, 2], "sampling_options": {}, "stop_conditions": {"max_tokens": 5}}
        got = []
        with pytest.raises(StreamDisconnect):
            async for item in engine.generate(request, Context()):
                data = item.data if hasattr(item, "data") else item
                if data:
                    got.extend(data.get("token_ids") or [])
        assert len(got) == 3  # one token per attempt, 1 + 2 retries
    finally:
        await drt.shutdown()
