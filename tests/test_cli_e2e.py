"""Full-stack CLI e2e: control-plane broker + worker CLI + frontend CLI as
separate OS processes, driven over HTTP — the closest equivalent of the
reference's serve tests (tests/serve/) on one host."""

import asyncio
import json
import os
import signal
import subprocess
import sys

import aiohttp
import pytest

from dynamo_tpu.runtime.transports.tcp_control import ControlPlaneServer


def spawn(args, port):
    env = dict(
        os.environ,
        DYN_CONTROL_PLANE="tcp",
        DYN_CONTROL_PLANE_ADDRESS=f"127.0.0.1:{port}",
        JAX_PLATFORMS="cpu",
        DYN_LOG="WARNING",
    )
    return subprocess.Popen(
        [sys.executable, "-m"] + args,
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


@pytest.mark.e2e
async def test_worker_frontend_cli_roundtrip():
    server = ControlPlaneServer(host="127.0.0.1", port=0)
    await server.start()
    procs = []
    try:
        procs.append(
            spawn(
                ["dynamo_tpu.worker", "--mocker", "--model", "mock-model", "--speedup-ratio", "50"],
                server.port,
            )
        )
        http_port = 18231
        procs.append(spawn(["dynamo_tpu.frontend", "--http-port", str(http_port), "--router-mode", "kv"], server.port))

        base = f"http://127.0.0.1:{http_port}"
        async with aiohttp.ClientSession() as s:
            # Wait for the model to appear through discovery.
            for _ in range(120):
                try:
                    async with s.get(f"{base}/v1/models") as r:
                        if r.status == 200 and (await r.json())["data"]:
                            break
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.25)
            else:
                pytest.fail("model never appeared via frontend discovery")

            body = {
                "model": "mock-model",
                "messages": [{"role": "user", "content": "hello from the cli e2e"}],
                "max_tokens": 5,
                "stream": True,
            }
            chunks = []
            async with s.post(f"{base}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                async for line in r.content:
                    line = line.decode().strip()
                    if line.startswith("data: ") and line != "data: [DONE]":
                        chunks.append(json.loads(line[6:]))
            finishes = [c["choices"][0].get("finish_reason") for c in chunks]
            assert "length" in finishes

            # Frontend metrics exposed.
            async with s.get(f"{base}/metrics") as r:
                text = await r.text()
                assert "dynamo_frontend_requests_total" in text
    finally:
        for p in procs:
            p.send_signal(signal.SIGKILL)
            p.wait()
        await server.close()
