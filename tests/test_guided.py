"""Guided decoding (structured outputs): token-FSM mask oracle, scheduler
greedy guided decode + compile-count bound, HTTP e2e (response_format /
forced tool_choice), protocol 400s, and mocker wire-path honor.

The oracle test is exact: for bounded-language specs it enumerates every
viable prefix with Python ``re`` as ground truth, then checks the token
mask bit-for-bit — every allowed token keeps the string matchable, every
disallowed token breaks it.
"""

import itertools
import json
import random
import re

import jax
import jax.numpy as jnp
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions
from dynamo_tpu.llm.guided.fsm import compile_token_fsm
from dynamo_tpu.llm.guided.grammar import (
    GrammarError,
    build_guided_spec,
    compile_regex,
    json_object_regex,
    schema_to_regex,
    spec_to_dfa,
)
from dynamo_tpu.llm.guided.processor import GuidedDecoder
from dynamo_tpu.llm.protocols import openai as oai
from dynamo_tpu.llm.tokenizer import ByteTokenizer

CFG = get_config("tiny")
EOS = 0
SCHEMA = {
    "type": "object",
    "properties": {"city": {"enum": ["SF", "NY"]}, "ok": {"type": "boolean"}},
}

_TOKEN_STRS = [ByteTokenizer().decode([i]) for i in range(256)]


def _params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _sched(**kw):
    base = dict(
        num_blocks=128,
        prefill_buckets=[16, 32, 64],
        decode_buckets=[1, 2, 4],
        num_scheduler_steps=1,
        enable_prefix_caching=False,
        guided_pool_rows=256,
    )
    base.update(kw)
    sched = Scheduler(CFG, _params(), SchedulerConfig(**base), dtype=jnp.float32, eos_token_ids=[EOS])
    sched.attach_guided(ByteTokenizer())
    return sched


def _drain(sched, max_steps=600):
    outs, fin = {}, {}
    steps = 0
    while sched.has_work() and steps < max_steps:
        steps += 1
        for seq, o in sched.step():
            if o.token_id >= 0:
                outs.setdefault(seq.request_id, []).append(o.token_id)
            if o.finished:
                fin[seq.request_id] = o.finish_reason
    assert not sched.has_work(), "scheduler did not drain"
    return outs, fin


# --- token-FSM mask oracle ---------------------------------------------------


def _viable_prefixes(pattern, charset, max_len):
    """Ground truth via Python re: all prefixes of the (bounded) language
    enumerated over ``charset`` up to ``max_len``."""
    viable = set()
    for n in range(max_len + 1):
        for combo in itertools.product(charset, repeat=n):
            s = "".join(combo)
            if re.fullmatch(pattern, s):
                for i in range(len(s) + 1):
                    viable.add(s[:i])
    return viable


ORACLE_SPECS = [
    # Finite languages only: the re-enumeration ground truth must cover the
    # WHOLE language within max_len for the viability check to be exact.
    ("(ab|cd){1,3}", "abcd", 6),
    ("a?b{1,2}c{2}", "abc", 5),
    ("[xy]{2,4}", "xy", 4),
    ("(foo|bar|foobar)", "fobar", 6),
    ('"(SF|NY)"', '"SFNY', 4),
    ("x(12|345)?y", "12345xy", 6),
]


def _random_choice_specs(rng, n=6):
    words = ["ab", "ba", "aab", "bba", "abb", "a", "b"]
    out = []
    for _ in range(n):
        picks = rng.sample(words, rng.randint(2, 4))
        out.append(("(?:" + "|".join(picks) + ")", "ab", max(len(w) for w in picks)))
    return out


def test_token_fsm_mask_oracle():
    rng = random.Random(7)
    for pattern, charset, max_len in ORACLE_SPECS + _random_choice_specs(rng):
        dfa = compile_regex(pattern)
        fsm = compile_token_fsm(dfa, _TOKEN_STRS, eos_ids=[EOS])
        viable = _viable_prefixes(pattern, charset, max_len)
        assert "" in viable, pattern
        for prefix in sorted(viable):
            state = 0
            for ch in prefix:
                state = int(fsm.next_state[state, ord(ch)])
            assert state >= 0, (pattern, prefix)
            for ch in charset:
                allowed = fsm.allows(state, ord(ch))
                assert allowed == ((prefix + ch) in viable), (pattern, prefix, ch)
            # EOS is allowed exactly when the prefix is a complete match.
            assert fsm.allows(state, EOS) == bool(re.fullmatch(pattern, prefix)), (
                pattern, prefix,
            )


def test_schema_fsm_random_walks_emit_valid_json():
    """Random mask-following walks over schema grammars always land on
    strings that re-fullmatch the schema regex AND json-parse."""
    rng = random.Random(3)
    schemas = [
        SCHEMA,
        {"type": "object", "properties": {
            "tags": {"type": "array", "items": {"enum": ["a", "b"]}, "maxItems": 3},
            "level": {"enum": [1, 2, 3]},
        }},
        {"type": "object", "properties": {
            "name": {"type": "string", "maxLength": 4},
            "score": {"anyOf": [{"type": "integer"}, {"type": "null"}]},
        }},
    ]
    for schema in schemas:
        pattern = schema_to_regex(schema)
        fsm = compile_token_fsm(compile_regex(pattern), _TOKEN_STRS, eos_ids=[EOS])
        for _ in range(10):
            state, chars = 0, []
            for _step in range(200):
                allowed = [t for t in range(1, 256) if fsm.allows(state, t)]
                if fsm.allows(state, EOS) and (not allowed or rng.random() < 0.5):
                    break
                tok = rng.choice(allowed)
                chars.append(chr(tok))
                state = int(fsm.next_state[state, tok])
            s = "".join(chars)
            assert re.fullmatch(pattern, s), (schema, s)
            json.loads(s)


def test_json_object_regex_and_dfa_agree_with_re():
    pattern = json_object_regex()
    dfa = compile_regex(pattern)
    good = ['{}', '{"a":1}', '{"a":{"b":[1,2]},"c":"x"}', '{"k":"v","l":[true,null]}']
    bad = ['{"k":}', '[1]', '{', '{"a" :1}', 'null']
    for s in good:
        assert dfa.match(s) and re.fullmatch(pattern, s), s
    for s in bad:
        assert not dfa.match(s) and not re.fullmatch(pattern, s), s


def test_grammar_rejections():
    for pattern in ["(?=a)b", "a**b[", "[z-a]", "(a", "a\\1", "^a$"]:
        with pytest.raises(GrammarError):
            compile_regex(pattern)
    for schema in [{"$ref": "#/defs/x"}, {"allOf": [{}]}, {"type": "object", "properties": {"a": {"$ref": "#"}}}]:
        with pytest.raises(GrammarError):
            schema_to_regex(schema)
    with pytest.raises(GrammarError):
        spec_to_dfa({"kind": "nope"})


# --- scheduler-level ---------------------------------------------------------


def test_scheduler_greedy_guided_yields_schema_valid_json():
    sched = _sched()
    pattern = schema_to_regex(SCHEMA)
    sched.add_request(
        "g", list(range(1, 17)), SamplingParams(temperature=0.0),
        StopConditions(max_tokens=64), guided={"kind": "regex", "pattern": pattern},
    )
    outs, fin = _drain(sched)
    text = ByteTokenizer().decode(outs["g"])
    assert fin["g"] == "stop"
    assert re.fullmatch(pattern, text)
    obj = json.loads(text)
    assert obj["city"] in ("SF", "NY") and isinstance(obj["ok"], bool)
    assert sched.guided.stats()["guided_requests_total"] == 1


def test_guided_row_does_not_perturb_unguided_batchmates():
    """Unguided rows in a batch that carries a guided row sample through the
    allow-all pool row — their greedy outputs must equal a run without the
    guided row."""
    ref = _sched()
    for i in range(2):
        ref.add_request(f"u{i}", list(range(1 + i, 17 + i)), SamplingParams(temperature=0.0),
                        StopConditions(max_tokens=12))
    want, _ = _drain(ref)

    sched = _sched()
    for i in range(2):
        sched.add_request(f"u{i}", list(range(1 + i, 17 + i)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=12))
    sched.add_request(
        "g", list(range(5, 21)), SamplingParams(temperature=0.0),
        StopConditions(max_tokens=48),
        guided={"kind": "regex", "pattern": schema_to_regex(SCHEMA)},
    )
    got, fin = _drain(sched)
    assert got["u0"] == want["u0"] and got["u1"] == want["u1"]
    assert fin["g"] == "stop"
    json.loads(ByteTokenizer().decode(got["g"]))


def test_guided_choice_and_sampled_temperature():
    """Non-greedy guided sampling still honors the mask (whatever the
    temperature draws, it must be one of the choices)."""
    sched = _sched()
    sched.add_request(
        "c", list(range(1, 17)), SamplingParams(temperature=1.0, seed=11),
        StopConditions(max_tokens=16),
        guided={"kind": "choice", "choices": ["red", "green", "blue"]},
    )
    outs, fin = _drain(sched)
    assert fin["c"] == "stop"
    assert ByteTokenizer().decode(outs["c"]) in ("red", "green", "blue")


def test_guided_no_compiles_after_warmup():
    """Guided rows joining a warmed batch add no post-warmup XLA compiles
    (flight-recorder-verified): the masked-sampling executables are part of
    warmup()'s serving set."""
    sched = _sched(enable_mixed_batching=False)
    sched.warmup(128)
    sched.flight.mark_warmup_done(warmed=True)
    pattern = schema_to_regex(SCHEMA)
    # Staggered adds: admission paths (single prefill) all warmed; guided
    # rows then ride the batched decode + bucket-1 first-token sampler.
    sched.add_request("u0", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=40))
    for _ in range(3):
        sched.step()
    sched.add_request("g", list(range(3, 19)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=64), guided={"kind": "regex", "pattern": pattern})
    for _ in range(3):
        sched.step()
    sched.add_request("u1", list(range(7, 23)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=30))
    _, fin = _drain(sched)
    assert fin["g"] == "stop"
    assert sched.flight.compiles_after_warmup_total == 0, sched.flight.post_warmup_keys


def test_guided_rides_mixed_steps():
    """A guided head-of-queue prompt rides mixed prefill+decode dispatches
    and still emits grammar-valid output."""
    sched = _sched(enable_mixed_batching=True, mixed_prefill_budget=32)
    sched.add_request("d", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=60))
    for _ in range(3):
        sched.step()
    pattern = schema_to_regex(SCHEMA)
    sched.add_request("g", list(range(2, 50)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=64), guided={"kind": "regex", "pattern": pattern})
    outs, fin = _drain(sched)
    assert sched.mixed_steps_total >= 1
    assert fin["g"] == "stop"
    assert re.fullmatch(pattern, ByteTokenizer().decode(outs["g"]))


def test_guided_with_spec_decode_falls_back_gracefully():
    """A guided row in a draft-attached engine keeps the batch on the
    non-speculative path (no spec rounds) and still emits valid output."""
    sched = _sched()
    draft_params = llama.init_params(CFG, jax.random.PRNGKey(9), dtype=jnp.float32)
    sched.attach_draft(CFG, draft_params, gamma=2)
    pattern = schema_to_regex(SCHEMA)
    sched.add_request("g", list(range(1, 17)), SamplingParams(temperature=0.0),
                      StopConditions(max_tokens=64), guided={"kind": "regex", "pattern": pattern})
    outs, fin = _drain(sched)
    assert fin["g"] == "stop"
    assert re.fullmatch(pattern, ByteTokenizer().decode(outs["g"]))
    assert sched.spec_stats.num_rounds == 0


def test_guided_requires_attached_tokenizer():
    sched = Scheduler(CFG, _params(), SchedulerConfig(num_blocks=64), dtype=jnp.float32)
    with pytest.raises(ValueError, match="tokenizer"):
        sched.add_request("g", [1, 2, 3], SamplingParams(), StopConditions(),
                          guided={"kind": "regex", "pattern": "ab"})


# --- protocol validation -----------------------------------------------------


def _chat_body(**extra):
    return {"model": "m", "messages": [{"role": "user", "content": "x"}], **extra}


def test_protocol_response_format_and_tool_choice_400s():
    bad = [
        _chat_body(response_format="json"),
        _chat_body(response_format={"type": "nope"}),
        _chat_body(response_format={"type": "json_schema"}),
        _chat_body(response_format={"type": "json_schema", "json_schema": {}}),
        _chat_body(tools=[{"type": "function"}]),
        _chat_body(tools=[{"type": "function", "function": {"name": "a"}}],
                   tool_choice={"type": "function", "function": {"name": "b"}}),
        _chat_body(tool_choice="required"),  # no tools
        _chat_body(tool_choice="maybe"),
        _chat_body(nvext={"guided_regex": ""}),
        _chat_body(nvext={"guided_choice": []}),
        _chat_body(nvext={"guided_regex": "a", "guided_choice": ["b"]}),
    ]
    for body in bad:
        with pytest.raises(oai.RequestError):
            oai.validate_chat_request(body)
    # Good shapes pass.
    oai.validate_chat_request(_chat_body(
        response_format={"type": "json_schema", "json_schema": {"name": "x", "schema": SCHEMA}},
        tools=[{"type": "function", "function": {"name": "a", "parameters": SCHEMA}}],
        tool_choice={"type": "function", "function": {"name": "a"}},
    ))
    oai.validate_chat_request(_chat_body(tool_choice="auto"))


def test_build_guided_spec_precedence_and_400s():
    # Forced tool choice wins over response_format.
    spec = build_guided_spec(_chat_body(
        tools=[{"type": "function", "function": {"name": "f", "parameters": SCHEMA}}],
        tool_choice="required",
        response_format={"type": "json_object"},
    ))
    assert spec["source"] == "tool_choice" and spec["forced_tools"] == ["f"]
    # Unsupported schema constructs are structured 400s.
    with pytest.raises(oai.RequestError):
        build_guided_spec(_chat_body(
            response_format={"type": "json_schema",
                             "json_schema": {"schema": {"$ref": "#/x"}}},
        ))
    with pytest.raises(oai.RequestError):
        build_guided_spec(_chat_body(nvext={"guided_regex": "(?=a)b"}))
    # tool_choice auto / none / plain text produce no constraint.
    assert build_guided_spec(_chat_body(tool_choice="auto")) is None
    assert build_guided_spec(_chat_body(response_format={"type": "text"})) is None


def test_responses_text_format_translation():
    body = {"model": "m", "input": "hi",
            "text": {"format": {"type": "json_schema", "name": "x", "schema": SCHEMA}}}
    rf = oai.responses_text_format_to_response_format(body)
    assert rf == {"type": "json_schema", "json_schema": {"name": "x", "schema": SCHEMA}}
    assert oai.responses_tool_choice_to_chat({"type": "function", "name": "f"}) == {
        "type": "function", "function": {"name": "f"}}
    assert oai.responses_tool_choice_to_chat("auto") == "auto"


# --- HTTP e2e ----------------------------------------------------------------


async def _service():
    import aiohttp  # noqa: F401 — fail fast if missing

    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.llm.discovery import ModelManager
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.http.service import HttpService

    engine = TpuEngine.build(EngineArgs(
        model="tiny", dtype="float32", eos_token_ids=[EOS],
        scheduler=SchedulerConfig(
            num_blocks=64, prefill_buckets=[16, 32, 64, 128],
            decode_buckets=[1, 2, 4, 8], guided_pool_rows=256,
        ),
    ))
    manager = ModelManager()
    manager.add_model("chat", "tiny-chat", build_local_pipeline(ByteTokenizer(), engine))
    service = HttpService(manager, host="127.0.0.1", port=0)
    await service.start()
    return service, engine


async def test_http_response_format_json_schema_roundtrip():
    import aiohttp

    service, engine = await _service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "city?"}],
                "max_tokens": 64, "temperature": 0,
                "response_format": {"type": "json_schema",
                                    "json_schema": {"name": "city", "schema": SCHEMA}},
            }
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        choice = data["choices"][0]
        assert choice["finish_reason"] == "stop"
        obj = json.loads(choice["message"]["content"])
        assert obj["city"] in ("SF", "NY") and isinstance(obj["ok"], bool)
    finally:
        await service.stop()
        await engine.stop()


async def test_http_forced_tool_choice_roundtrips_parser():
    import aiohttp

    service, engine = await _service()
    try:
        async with aiohttp.ClientSession() as s:
            body = {
                "model": "tiny-chat",
                "messages": [{"role": "user", "content": "call the tool"}],
                "max_tokens": 96, "temperature": 0,
                "tools": [{"type": "function",
                           "function": {"name": "get_city", "parameters": SCHEMA}}],
                "tool_choice": {"type": "function", "function": {"name": "get_city"}},
            }
            async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        choice = data["choices"][0]
        assert choice["finish_reason"] == "tool_calls"
        call = choice["message"]["tool_calls"][0]
        assert call["function"]["name"] == "get_city"
        args = json.loads(call["function"]["arguments"])
        assert args["city"] in ("SF", "NY") and isinstance(args["ok"], bool)
    finally:
        await service.stop()
        await engine.stop()


async def test_http_guided_400s_never_500s():
    import aiohttp

    service, engine = await _service()
    try:
        async with aiohttp.ClientSession() as s:
            for bad in [
                {"response_format": {"type": "json_schema"}},
                {"response_format": {"type": "bogus"}},
                {"response_format": {"type": "json_schema",
                                     "json_schema": {"schema": {"$ref": "#/x"}}}},
                {"tools": [{"type": "function", "function": {"name": "a"}}],
                 "tool_choice": {"type": "function", "function": {"name": "b"}}},
                {"nvext": {"guided_regex": "(?=x)y"}},
            ]:
                body = {"model": "tiny-chat",
                        "messages": [{"role": "user", "content": "x"}],
                        "max_tokens": 4, **bad}
                async with s.post(f"http://127.0.0.1:{service.port}/v1/chat/completions", json=body) as r:
                    assert r.status == 400, (bad, r.status, await r.text())
                    assert "error" in await r.json()
    finally:
        await service.stop()
        await engine.stop()


# --- mocker wire path --------------------------------------------------------


async def test_mocker_honors_guided_requests():
    from dynamo_tpu.llm.entrypoint import build_local_pipeline
    from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
    from dynamo_tpu.runtime.engine import Annotated, Context

    engine = MockTpuEngine(MockEngineArgs(speedup_ratio=50.0))
    pipe = build_local_pipeline(ByteTokenizer(), engine)

    async def run(body):
        text, finish, calls = [], None, None
        async for item in pipe.generate(body, Context()):
            if isinstance(item, Annotated) and item.is_annotation():
                continue
            wire = item.data if isinstance(item, Annotated) else item
            if wire.get("text"):
                text.append(wire["text"])
            if wire.get("tool_calls"):
                calls = wire["tool_calls"]
            if wire.get("finish_reason"):
                finish = wire["finish_reason"]
        return "".join(text), finish, calls

    text, finish, _ = await run({
        "model": "mock", "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 64,
        "response_format": {"type": "json_schema",
                            "json_schema": {"name": "c", "schema": SCHEMA}},
    })
    assert finish == "stop"
    obj = json.loads(text)
    assert obj["city"] in ("SF", "NY")
    assert engine.guided_total == 1

    _, finish, calls = await run({
        "model": "mock", "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 96,
        "tools": [{"type": "function", "function": {"name": "get_city", "parameters": SCHEMA}}],
        "tool_choice": "required",
    })
    assert finish == "tool_calls"
    assert calls[0]["function"]["name"] == "get_city"
    json.loads(calls[0]["function"]["arguments"])
