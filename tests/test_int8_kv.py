"""int8 KV cache: quantized storage with per-(token, head) scales.
Parity within quantization tolerance across prefill/decode/window/chunk
paths, exact requant round-trips through the transfer boundary (KVBM /
disagg payloads stay real-valued), and serving e2e."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays, QuantKv, dequantize_kv, quantize_kv_rows
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import Scheduler, SchedulerConfig, StopConditions

CFG = get_config("tiny")
CFG8 = CFG.replace(kv_cache_dtype="int8")


def test_quantize_roundtrip_stable():
    """Requantizing dequantized rows is stable to within one code step
    (float rounding of scale*127/127 can nudge borderline codes by ±1) —
    the transfer boundary (disagg/KVBM) tolerance."""
    rows = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 2, 16), dtype=jnp.float32) * 3.0
    q1 = quantize_kv_rows(rows)
    deq1 = np.asarray(dequantize_kv(q1, dtype=jnp.float32))
    q2 = quantize_kv_rows(jnp.asarray(deq1))
    deq2 = np.asarray(dequantize_kv(q2, dtype=jnp.float32))
    step = np.asarray(q1.scale)  # one code step per (token, head)
    np.testing.assert_allclose(deq2, deq1, atol=float(step.max()) * 1.01)
    np.testing.assert_allclose(np.asarray(q1.scale), np.asarray(q2.scale), rtol=1e-5)


def test_config_guards():
    # MLA int8 latents are supported since r4 (per-token latent-row scale).
    assert get_config("tiny-mla").replace(kv_cache_dtype="int8").kv_cache_dtype == "int8"
    with pytest.raises(ValueError, match="attention_impl"):
        CFG.replace(attention_impl="paged_kernel")  # deleted r4


def test_prefill_decode_parity_within_tolerance():
    """Same weights, int8 vs full-precision KV: logits agree to quantization
    tolerance through prefill + several decode steps."""
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = jnp.arange(20, 36, dtype=jnp.int32)
    table = jnp.array([1, 2, 3, 0], dtype=jnp.int32)

    def run(cfg):
        cache = KvCacheArrays.create(cfg, 24, dtype=jnp.float32)
        logits, k, v = llama.prefill(params, cfg, cache.k, cache.v, prompt,
                                     jnp.int32(16), jnp.int32(0), table)
        outs = [np.asarray(logits)]
        tok = jnp.array([int(jnp.argmax(logits)), 0], dtype=jnp.int32)
        tables = jnp.zeros((2, 4), dtype=jnp.int32).at[0].set(table)
        active = jnp.array([True, False])
        for i in range(4):
            logits, k, v = llama.decode(params, cfg, k, v, tok,
                                        jnp.array([16 + i, 0], dtype=jnp.int32), tables, active)
            outs.append(np.asarray(logits[0]))
            tok = jnp.array([int(jnp.argmax(logits[0])), 0], dtype=jnp.int32)
        return outs

    ref = run(CFG)
    q = run(CFG8)
    for a, b in zip(ref, q):
        # int8 KV error is small but nonzero; logits must stay close.
        np.testing.assert_allclose(a, b, rtol=0.25, atol=0.25)


def test_scheduler_serves_with_int8_kv():
    """Full serving stack on a quantized cache: multi-step windows, prefix
    caching, preemption machinery all run; output matches the same engine's
    own determinism."""
    params = llama.init_params(CFG8, jax.random.PRNGKey(0), dtype=jnp.float32)

    def serve():
        s = Scheduler(CFG8, params, SchedulerConfig(
            num_blocks=48, prefill_buckets=[16, 32], decode_buckets=[1, 2, 4],
            num_scheduler_steps=4), dtype=jnp.float32)
        for i in range(2):
            s.add_request(f"r{i}", list(range(5 + i, 21 + i)), SamplingParams(temperature=0.0),
                          StopConditions(max_tokens=10))
        produced = {}
        for _ in range(300):
            if not s.has_work():
                break
            for seq, out in s.step():
                produced.setdefault(seq.request_id, []).append(out.token_id)
        assert not s.has_work()
        return {r: [t for t in ts if t >= 0] for r, ts in produced.items()}

    a = serve()
    b = serve()
    assert a == b  # deterministic
    assert all(len(v) == 10 for v in a.values())


def test_transfer_roundtrip_and_kvbm_with_int8():
    """gather/scatter blocks through the host boundary on a quantized cache:
    payload is real-valued, round trip is dequant-exact; KVBM offload →
    onboard preserves contents."""
    from dynamo_tpu.engine.kv_cache import BlockAllocator
    from dynamo_tpu.llm.block_manager import KvBlockManager
    from dynamo_tpu.llm.block_manager.transfer import gather_blocks, scatter_blocks
    from dynamo_tpu.llm.tokens import compute_block_hashes

    cache = KvCacheArrays.create(CFG8, 6, dtype=jnp.float32)
    rows = np.random.RandomState(0).randn(
        CFG.num_layers, CFG.block_size, CFG.num_kv_heads, CFG.head_dim
    ).astype(np.float32)
    scatter_blocks(cache, 2, rows, -rows)
    k_np, v_np = gather_blocks(cache, 2)
    # Quantization round trip: gather returns the dequantized values and a
    # second scatter/gather reproduces them exactly.
    scatter_blocks(cache, 3, k_np, v_np)
    k2, v2 = gather_blocks(cache, 3)
    step = np.abs(k_np).max() / 127
    np.testing.assert_allclose(k_np, k2, atol=step * 1.01)
    np.testing.assert_allclose(v_np, v2, atol=step * 1.01)
    # And the dequantized values are close to the originals.
    np.testing.assert_allclose(k_np, rows, atol=np.abs(rows).max() / 100)

    # KVBM offload → onboard over the quantized cache.
    alloc = BlockAllocator(6)
    alloc._free.remove(0)
    kvbm = KvBlockManager(cache, alloc, host_blocks=4)
    hashes = compute_block_hashes(list(range(32)), 16)
    blocks = alloc.allocate(2)
    contents = {}
    for b, h in zip(blocks, hashes):
        scatter_blocks(cache, b, rows + b, -(rows + b))
        contents[h] = gather_blocks(cache, b)[0]
    alloc.register_hashes(blocks, hashes)
    alloc.release(blocks)
    got = alloc.allocate(5)  # exhaust the pool: both cached blocks evict → G2
    kvbm.flush_pending()  # async offload: host transfer batches at drain
    assert kvbm.metrics.offloads_g2 == 2
    alloc.release(got)
    match = kvbm.match_prefix(hashes)
    onboarded = kvbm.onboard(match, hashes)
    assert len(onboarded) == 2
    for b, h in zip(onboarded, hashes):
        got = gather_blocks(cache, b)[0]
        step = np.abs(contents[h]).max() / 127
        np.testing.assert_allclose(got, contents[h], atol=step * 1.01)


async def test_engine_e2e_int8():
    from dynamo_tpu.engine.engine import EngineArgs, TpuEngine
    from dynamo_tpu.runtime.engine import Context

    engine = TpuEngine.build(EngineArgs(
        model="tiny", dtype="float32", kv_cache_dtype="int8",
        scheduler=SchedulerConfig(num_blocks=64, prefill_buckets=[16, 32, 64],
                                  decode_buckets=[1, 2, 4]),
    ))
    try:
        out = []
        async for frame in engine.generate(
            {"token_ids": list(range(20, 40)), "sampling_options": {"temperature": 0.0},
             "stop_conditions": {"max_tokens": 8}}, Context()):
            out.extend(frame["token_ids"])
        assert len(out) == 8
    finally:
        await engine.stop()


def test_mla_int8_latent_parity():
    """MLA latent rows under int8: prefill + decode logits agree with the
    full-precision cache to quantization tolerance (VERDICT r3 #10)."""
    from dynamo_tpu.engine.models import mla

    cfg = get_config("tiny-mla")
    params = mla.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 255, 20), jnp.int32)
    table = jnp.asarray(np.pad(np.arange(1, 4, dtype=np.int32), (0, 13)))

    def run(kv_dtype):
        c = cfg.replace(kv_cache_dtype=kv_dtype)
        cache = KvCacheArrays.create(c, num_blocks=16, dtype=jnp.float32)
        lg, k, v = mla.prefill(
            params, c, cache.k, cache.v, jnp.pad(toks, (0, 12)),
            jnp.int32(20), jnp.int32(0), table,
        )
        tables = jnp.asarray(np.pad(np.arange(1, 4, dtype=np.int32), (0, 1)))[None, :]
        dlg, _, _ = mla.decode(
            params, c, k, v, jnp.asarray([3], jnp.int32), jnp.asarray([20], jnp.int32),
            tables, jnp.asarray([True]),
        )
        return np.asarray(lg), np.asarray(dlg)

    lg_f, dlg_f = run("auto")
    lg_q, dlg_q = run("int8")
    np.testing.assert_allclose(lg_q, lg_f, rtol=0.1, atol=0.15)
    np.testing.assert_allclose(dlg_q, dlg_f, rtol=0.1, atol=0.15)
    # And the distributions agree where it matters: same greedy token.
    assert int(np.argmax(lg_q)) == int(np.argmax(lg_f))
    assert int(np.argmax(dlg_q)) == int(np.argmax(dlg_f))
