"""Correctness tests for the paged Llama forward pass: paged prefill+decode
must match a naive dense-attention reference implementation, including prefix
reuse and chunked prefill paths."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import BlockAllocator, KvCacheArrays
from dynamo_tpu.engine.models import llama

CFG = get_config("tiny").replace(dtype="float32")  # f32 on CPU for tight tolerances
DTYPE = jnp.float32


def naive_forward(params, config, tokens):
    """Dense causal transformer over the whole sequence; returns logits [T, V]."""
    c = config
    T = len(tokens)
    h = params["embed"][jnp.array(tokens)]
    positions = jnp.arange(T)
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    for l in range(c.num_layers):
        lp = {k: v[l] for k, v in params["layers"].items()}
        x = llama.rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = llama.apply_rope((x @ lp["wq"]).reshape(T, c.num_heads, c.head_dim), positions, c.rope_theta)
        k = llama.apply_rope((x @ lp["wk"]).reshape(T, c.num_kv_heads, c.head_dim), positions, c.rope_theta)
        v = (x @ lp["wv"]).reshape(T, c.num_kv_heads, c.head_dim)
        attn = llama._attend(q, k, v, mask, c)
        h = h + attn.reshape(T, c.q_size) @ lp["wo"]
        x = llama.rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
        h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
    h = llama.rms_norm(h, params["final_norm"], c.rms_norm_eps)
    head = params.get("lm_head", params["embed"].T)
    return (h @ head).astype(jnp.float32)


@pytest.fixture(scope="module")
def setup():
    params = llama.init_params(CFG, jax.random.PRNGKey(0), dtype=DTYPE)
    return params


def make_cache(num_blocks=32):
    cache = KvCacheArrays.create(CFG, num_blocks, dtype=DTYPE)
    return cache.k, cache.v


def test_prefill_matches_naive(setup):
    params = setup
    tokens = list(range(10, 31))  # 21 tokens
    T = len(tokens)
    bucket = 32
    k_cache, v_cache = make_cache()
    n_blocks = (T + CFG.block_size - 1) // CFG.block_size
    block_table = jnp.array([1, 2, 3, 0][: max(n_blocks, 4)], dtype=jnp.int32)

    padded = jnp.array(tokens + [0] * (bucket - T), dtype=jnp.int32)
    logits, k_cache, v_cache = llama.prefill(
        params, CFG, k_cache, v_cache, padded, jnp.int32(T), jnp.int32(0), block_table
    )
    ref = naive_forward(params, CFG, tokens)
    np.testing.assert_allclose(logits, ref[-1], rtol=2e-4, atol=2e-4)


def test_decode_matches_naive(setup):
    """Prefill n tokens then decode 5 more; logits at each decode step must
    match the dense forward over the growing sequence."""
    params = setup
    prompt = list(range(50, 60))
    k_cache, v_cache = make_cache()
    block_table = jnp.array([1, 2, 3, 4], dtype=jnp.int32)
    bucket = 16
    padded = jnp.array(prompt + [0] * (bucket - len(prompt)), dtype=jnp.int32)
    logits, k_cache, v_cache = llama.prefill(
        params, CFG, k_cache, v_cache, padded, jnp.int32(len(prompt)), jnp.int32(0), block_table
    )
    seq = list(prompt)
    B = 4  # decode batch bucket; only slot 0 active
    tables = jnp.zeros((B, 4), dtype=jnp.int32).at[0].set(block_table)
    for step in range(5):
        next_tok = int(jnp.argmax(logits)) if step == 0 else int(jnp.argmax(logits[0]))
        seq.append(next_tok)
        pos = len(seq) - 1
        toks = jnp.zeros((B,), dtype=jnp.int32).at[0].set(next_tok)
        positions = jnp.zeros((B,), dtype=jnp.int32).at[0].set(pos)
        active = jnp.zeros((B,), dtype=bool).at[0].set(True)
        logits, k_cache, v_cache = llama.decode(
            params, CFG, k_cache, v_cache, toks, positions, tables, active
        )
        ref = naive_forward(params, CFG, seq)
        np.testing.assert_allclose(logits[0], ref[-1], rtol=2e-4, atol=2e-4)


def test_chunked_prefill_matches_full(setup):
    """Prefill in two chunks (cache_len offset) ≡ one-shot prefill."""
    params = setup
    tokens = list(range(7, 7 + 24))
    block_table = jnp.array([1, 2, 3, 4], dtype=jnp.int32)

    k1, v1 = make_cache()
    padded = jnp.array(tokens + [0] * (32 - 24), dtype=jnp.int32)
    full_logits, _, _ = llama.prefill(params, CFG, k1, v1, padded, jnp.int32(24), jnp.int32(0), block_table)

    k2, v2 = make_cache()
    chunk1 = jnp.array(tokens[:16], dtype=jnp.int32)
    _, k2, v2 = llama.prefill(params, CFG, k2, v2, chunk1, jnp.int32(16), jnp.int32(0), block_table)
    chunk2 = jnp.array(tokens[16:] + [0] * 8, dtype=jnp.int32)
    chunk_logits, _, _ = llama.prefill(params, CFG, k2, v2, chunk2, jnp.int32(8), jnp.int32(16), block_table)

    np.testing.assert_allclose(chunk_logits, full_logits, rtol=2e-4, atol=2e-4)


def test_prefix_reuse_via_shared_blocks(setup):
    """Two sequences sharing a 16-token prefix: seq B reuses seq A's first
    block (cache_len=16) and must match a from-scratch forward."""
    params = setup
    prefix = list(range(100, 116))  # exactly one block
    suffix_b = [7, 8, 9, 10]

    k, v = make_cache()
    # Seq A prefills the shared prefix into block 1.
    table_a = jnp.array([1, 2, 0, 0], dtype=jnp.int32)
    _, k, v = llama.prefill(params, CFG, k, v, jnp.array(prefix, dtype=jnp.int32), jnp.int32(16), jnp.int32(0), table_a)

    # Seq B: block table starts with the shared block 1, new block 3.
    table_b = jnp.array([1, 3, 0, 0], dtype=jnp.int32)
    padded_b = jnp.array(suffix_b + [0] * 12, dtype=jnp.int32)
    logits_b, _, _ = llama.prefill(params, CFG, k, v, padded_b, jnp.int32(4), jnp.int32(16), table_b)

    ref = naive_forward(params, CFG, prefix + suffix_b)
    np.testing.assert_allclose(logits_b, ref[-1], rtol=2e-4, atol=2e-4)


def test_block_allocator_prefix_caching():
    from dynamo_tpu.llm.tokens import compute_block_hashes

    events = []
    alloc = BlockAllocator(num_blocks=8, on_event=events.append)
    tokens = list(range(64))  # 4 blocks of 16
    hashes = compute_block_hashes(tokens, 16)

    blocks = alloc.allocate(4)
    alloc.register_hashes(blocks, hashes)
    assert events[-1].kind == "stored" and len(events[-1].block_hashes) == 4

    # Release → blocks become cached, matchable.
    alloc.release(blocks)
    assert alloc.num_cached == 4

    matched = alloc.match_prefix(hashes[:2])
    assert matched == blocks[:2]
    assert alloc.num_cached == 2

    # Allocate enough to force LRU eviction of remaining cached blocks.
    got = alloc.allocate(6)
    assert len(got) == 6
    removed = [e for e in events if e.kind == "removed"]
    assert removed and len(removed[-1].block_hashes) == 2

    alloc.release(matched)
    alloc.release(got)
    assert alloc.num_free == 8


def test_block_allocator_oom():
    alloc = BlockAllocator(num_blocks=4)
    alloc.allocate(4)
    import pytest as _p

    with _p.raises(Exception):
        alloc.allocate(1)
