"""Profile the serving plane: where does the time per request go?

Runs the bench_http_e2e stack (tiny model, CPU ok) with instrumentation:
- scheduler.step() wall time, split prefill/decode, + counts
- engine loop iterations and to_thread overhead
- HTTP-level req/s + tok/s

Usage: python tools/profile_serving.py [n_requests] [concurrency]
"""

import asyncio
import cProfile
import io
import pstats
import sys
import time

import bench


def main():
    n_requests = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    concurrency = int(sys.argv[2]) if len(sys.argv) > 2 else 12

    import dynamo_tpu.engine.scheduler as sched_mod

    stats = {"step_calls": 0, "step_s": 0.0, "prefill_calls": 0, "prefill_s": 0.0,
             "decode_calls": 0, "decode_s": 0.0, "sample_one_calls": 0, "sample_one_s": 0.0}

    orig_step = sched_mod.Scheduler.step
    orig_prefill = sched_mod.Scheduler._prefill_one
    orig_decode = sched_mod.Scheduler._decode_step
    orig_sample1 = sched_mod.Scheduler._sample_one

    def timed(name, orig):
        def wrap(self, *a, **kw):
            t0 = time.perf_counter()
            try:
                return orig(self, *a, **kw)
            finally:
                stats[f"{name}_calls"] += 1
                stats[f"{name}_s"] += time.perf_counter() - t0
        return wrap

    sched_mod.Scheduler.step = timed("step", orig_step)
    sched_mod.Scheduler._prefill_one = timed("prefill", orig_prefill)
    sched_mod.Scheduler._decode_step = timed("decode", orig_decode)
    sched_mod.Scheduler._sample_one = timed("sample_one", orig_sample1)

    t0 = time.perf_counter()
    res = bench.bench_http_e2e(n_requests=n_requests, concurrency=concurrency)
    wall = time.perf_counter() - t0
    print("http_e2e:", res)
    print(f"wall {wall:.1f}s")
    for k in ("step", "prefill", "decode", "sample_one"):
        calls, secs = stats[f"{k}_calls"], stats[f"{k}_s"]
        if calls:
            print(f"{k:12s}: {calls:5d} calls, {secs:7.2f}s total, {secs/calls*1e3:7.2f} ms/call")
    other = stats["step_s"] - stats["prefill_s"] - stats["decode_s"]
    print(f"{'step other':12s}: {other:7.2f}s (reap/admit bookkeeping)")
    print(f"{'outside step':12s}: {wall - stats['step_s']:7.2f}s (HTTP, detok, asyncio, idle)")


if __name__ == "__main__":
    main()
