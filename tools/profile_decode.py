"""Decode-step profiling harness: isolate where step time goes.

Variants measured at the bench config (llama-3.2-1b, b8, ctx1024):
- full        : current decode (gather attention)
- kernel      : current decode (pallas paged kernel)
- no_attn     : attention replaced with identity (isolates weights traffic)
- no_lm_head  : logits head removed
- matmul_only : pure streamed-weights matmul chain (HBM bandwidth ceiling)

Prints ms/step + achieved HBM GB/s per variant.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama


def timeit(fn, *args, iters=50, donate=()):
    out = fn(*args)
    jax.block_until_ready(out)
    # re-fetch donated args each time is wrong; instead loop with carried outputs when donating
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1000


def main():
    model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    B = int(os.environ.get("BENCH_BATCH", "8"))
    ctx = int(os.environ.get("BENCH_CTX", "1024"))
    cfg = get_config(model).replace(max_seq_len=2048)
    num_blocks = B * (ctx // cfg.block_size + 4) + 8

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)

    needed = (ctx + 64) // cfg.block_size
    width = min((needed + 15) // 16 * 16, cfg.max_seq_len // cfg.block_size)
    tables = np.zeros((B, width), dtype=np.int32)
    for i in range(B):
        base = 1 + i * (ctx // cfg.block_size)
        tables[i, :needed] = (np.arange(needed) + base) % (num_blocks - 1) + 1
    tables = jnp.asarray(tables)
    active = jnp.ones((B,), dtype=bool)
    toks = jnp.zeros((B,), dtype=jnp.int32)
    pos = jnp.full((B,), ctx, dtype=jnp.int32)

    param_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    kv_read_bytes = 2 * cfg.num_layers * ctx * cfg.num_kv_heads * cfg.head_dim * 2 * B
    print(f"params: {param_bytes/1e9:.3f} GB   kv-read/step: {kv_read_bytes/1e9:.3f} GB  width={width} blocks")

    results = {}

    # --- full decode (gather) ---
    for name, impl in (("gather", "gather"),):
        c = cfg.replace(attention_impl=impl)
        step = jax.jit(
            lambda p, k, v, t, po: llama.decode(p, c, k, v, t, po, tables, active),
            donate_argnums=(1, 2),
        )
        k, v = jnp.copy(cache.k), jnp.copy(cache.v)
        logits, k, v = step(params, k, v, toks, pos)
        jax.block_until_ready(logits)
        n = 50
        t0 = time.perf_counter()
        for _ in range(n):
            logits, k, v = step(params, k, v, toks, pos)
        jax.block_until_ready(logits)
        ms = (time.perf_counter() - t0) / n * 1000
        results[name] = ms
        cost = step.lower(params, k, v, toks, pos).compile().cost_analysis()
        ba = cost.get("bytes accessed", 0) if cost else 0
        print(f"{name:12s}: {ms:7.3f} ms  ({(param_bytes+kv_read_bytes)/ms*1e-6:7.1f} GB/s useful)  bytes_accessed={ba/1e9:.2f} GB")

    # --- no attention: isolate weight streaming ---
    def decode_no_attn(p, t):
        h = p["embed"].at[t].get(mode="clip")

        def layer_fn(carry, lp):
            h = carry
            x = llama.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
            q = x @ lp["wq"]
            kk = x @ lp["wk"]
            vv = x @ lp["wv"]
            attn = q + jnp.concatenate([kk, vv, kk, vv], axis=-1) * 0  # keep shapes
            h = h + attn @ lp["wo"]
            x = llama.rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
            return h, None

        h, _ = lax.scan(layer_fn, h, p["layers"])
        h = llama.rms_norm(h, p["final_norm"], cfg.rms_norm_eps)
        return (h @ p["embed"].T).astype(jnp.float32)

    f = jax.jit(decode_no_attn)
    ms = timeit(f, params, toks)
    results["no_attn"] = ms
    print(f"{'no_attn':12s}: {ms:7.3f} ms  ({param_bytes/ms*1e-6:7.1f} GB/s weights)")

    # --- no lm_head ---
    def decode_no_head(p, t):
        h = p["embed"].at[t].get(mode="clip")

        def layer_fn(carry, lp):
            h = carry
            x = llama.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
            q = x @ lp["wq"]
            kk = x @ lp["wk"]
            vv = x @ lp["wv"]
            attn = q + jnp.concatenate([kk, vv, kk, vv], axis=-1) * 0
            h = h + attn @ lp["wo"]
            x = llama.rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
            return h, None

        h, _ = lax.scan(layer_fn, h, p["layers"])
        return h

    f = jax.jit(decode_no_head)
    ms = timeit(f, params, toks)
    results["no_head"] = ms
    print(f"{'no_head':12s}: {ms:7.3f} ms")

    # --- unrolled layers (no scan) ---
    def decode_unrolled(p, t):
        h = p["embed"].at[t].get(mode="clip")
        for l in range(cfg.num_layers):
            lp = {k2: v2[l] for k2, v2 in p["layers"].items()}
            x = llama.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
            q = x @ lp["wq"]
            kk = x @ lp["wk"]
            vv = x @ lp["wv"]
            attn = q + jnp.concatenate([kk, vv, kk, vv], axis=-1) * 0
            h = h + attn @ lp["wo"]
            x = llama.rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
            h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
        h = llama.rms_norm(h, p["final_norm"], cfg.rms_norm_eps)
        return (h @ p["embed"].T).astype(jnp.float32)

    f = jax.jit(decode_unrolled)
    ms = timeit(f, params, toks)
    results["unrolled_noattn"] = ms
    print(f"{'unrl_noattn':12s}: {ms:7.3f} ms  ({param_bytes/ms*1e-6:7.1f} GB/s weights)")

    # --- pure matmul chain: practical bandwidth ceiling ---
    mats = [jax.random.normal(jax.random.PRNGKey(i), (2048, 8192), dtype=jnp.bfloat16) for i in range(16 * 3)]

    def chain(x, mats):
        for i, m in enumerate(mats):
            if i % 2 == 0:
                x = x @ m
            else:
                x = x @ m.T
        return x

    x0 = jnp.ones((B, 2048), dtype=jnp.bfloat16)
    f = jax.jit(chain)
    ms = timeit(f, x0, mats)
    mat_bytes = sum(m.size * 2 for m in mats)
    results["matmul_chain"] = ms
    print(f"{'matmul':12s}: {ms:7.3f} ms  ({mat_bytes/ms*1e-6:7.1f} GB/s  {mat_bytes/1e9:.2f} GB)")


if __name__ == "__main__":
    main()
