"""Million-user traffic harness: drive a mocker fleet through load shapes
and close the planner loop under them.

"Millions of users" as a measured curve, not a claim: this harness offers
seeded open-loop traffic (Poisson arrivals — the superposition of a huge
independent user population) in the shapes production fleets actually see:

- **diurnal** — a day compressed to ``duration_s``: trough → crest → trough
  (raised-cosine), the shape the seasonal predictors must track;
- **flash** — flat baseline with a step to ``peak_rate`` (the flash crowd);
- **ramp** — linear trough→crest (the constant predictor's lag test);
- **noisy_flat** — flat with seeded multiplicative noise (the hysteresis
  test: quantile jitter must NOT flap the fleet).

ISL/OSL and the prefix-share ratio drift across the run (``isl_end`` etc.),
so prefill and decode demand move *independently* — exactly what forces
coordinated-but-independent pool scaling.

Requests traverse the real wire path disaggregated: a **prefill leg**
(``max_tokens=1``, KV-routed so same-prefix bursts concentrate and build
per-worker warmth) and a **decode leg** (``prefill_done`` — the mocker
admits it as transferred KV, simulating decode cost only). Both legs ride
``Migration``-wrapped KV routers, so drains and injected crashes replay
losslessly; with ``token_rule="position"`` every surviving request's token
stream is *bit-checkable* against its expected positions — the zero-token-
loss assertion is exact, not statistical.

``run_autoscale_bench`` stands up the whole plane in one process — fleet
(planner/fleet.py), metrics aggregator (multi-endpoint scrape), Prometheus
observer over a real HTTP /metrics, AutoscaleController — runs the
harness against it, optionally arms a chaos scenario (runtime/faults.py)
the moment the first scale event lands, and reports SLO-attainment +
goodput curves per window plus the controller's convergence vs the
capacity oracle. This is the standing ``autoscale`` bench section and the
CI gate.

CLI::

    python -m tools.traffic_harness --pattern diurnal --duration 30 \
        --base-rate 2 --peak-rate 10 --seed 0 --out autoscale.json
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import random
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional

from dynamo_tpu.runtime.logging import get_logger

logger = get_logger(__name__)


# --- offered load -------------------------------------------------------------
@dataclass
class Offered:
    rate: float  # req/s
    isl: int
    osl: int
    prefix_ratio: float


@dataclass
class TrafficPattern:
    kind: str = "diurnal"  # diurnal | flash | ramp | noisy_flat
    duration_s: float = 30.0
    base_rate: float = 2.0
    peak_rate: float = 10.0
    period_s: float = 0.0  # diurnal period; 0 = one full day over duration_s
    flash_at: float = 0.4  # flash window start/width, fractions of duration
    flash_len: float = 0.2
    isl: int = 96
    isl_end: Optional[int] = None  # drift targets; None = constant
    osl: int = 16
    osl_end: Optional[int] = None
    prefix_ratio: float = 0.5
    prefix_ratio_end: Optional[float] = None
    noise: float = 0.0  # multiplicative rate noise amplitude (seeded, per-second)
    seed: int = 0

    def _frac(self, t: float) -> float:
        return min(max(t / self.duration_s, 0.0), 1.0) if self.duration_s > 0 else 0.0

    def _drift(self, start: float, end: Optional[float], t: float) -> float:
        return start if end is None else start + (end - start) * self._frac(t)

    def rate(self, t: float) -> float:
        lo, hi = self.base_rate, self.peak_rate
        if self.kind == "diurnal":
            period = self.period_s or self.duration_s
            r = lo + (hi - lo) * 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period))
        elif self.kind == "flash":
            f = self._frac(t)
            r = hi if self.flash_at <= f < self.flash_at + self.flash_len else lo
        elif self.kind == "ramp":
            r = lo + (hi - lo) * self._frac(t)
        elif self.kind == "noisy_flat":
            r = lo
        else:
            raise ValueError(f"unknown pattern kind {self.kind!r}")
        if self.noise > 0:
            # Deterministic per-second jitter: a pure function of (seed, ⌊t⌋)
            # so two runs offer the identical load curve.
            jitter = random.Random((self.seed, int(t))).uniform(-self.noise, self.noise)
            r *= 1.0 + jitter
        return max(r, 0.0)

    def offered(self, t: float) -> Offered:
        return Offered(
            rate=self.rate(t),
            isl=int(round(self._drift(self.isl, self.isl_end, t))),
            osl=int(round(self._drift(self.osl, self.osl_end, t))),
            prefix_ratio=self._drift(self.prefix_ratio, self.prefix_ratio_end, t),
        )


class PromptFactory:
    """Deterministic prompts with a controllable shared-prefix ratio.

    ``groups`` hot prefixes model the popular system-prompt/context heads a
    real population shares; the suffix is unique per request. Token values
    are disjoint integer ranges so accidental overlap is impossible."""

    def __init__(self, block_size: int = 16, groups: int = 4):
        self.block_size = block_size
        self.groups = groups
        self._n = 0

    def make(self, rng: random.Random, isl: int, prefix_ratio: float) -> List[int]:
        bs = self.block_size
        plen = int(isl * prefix_ratio) // bs * bs  # block-aligned shared head
        g = rng.randrange(self.groups)
        prefix = [1_000_000 * (g + 1) + j for j in range(plen)]
        self._n += 1
        suffix = [500_000_000 + self._n * 8192 + j for j in range(max(isl - plen, 1))]
        return prefix + suffix


# --- per-request outcome ------------------------------------------------------
@dataclass
class Outcome:
    t: float  # arrival, seconds since harness start
    isl: int
    osl: int
    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None
    finish: Optional[str] = None
    error: Optional[str] = None
    tokens: int = 0
    token_exact: bool = False  # stream == expected positions, bit-for-bit

    @property
    def completed(self) -> bool:
        return self.error is None and self.finish in ("length", "stop")


class DisaggPath:
    """The two-leg disaggregated request path over mocker pools.

    TTFT is the prefill leg's first token (prompt processing happens
    there); the decode leg re-enters with ``prefill_done`` so the decode
    pool pays decode cost only. With ``token_rule="position"`` the decode
    stream must be exactly ``[isl, isl+1, ...]`` — surviving a drain or an
    injected crash with anything else is token loss and is counted."""

    def __init__(self, prefill_engine, decode_engine, *, request_timeout_ms: float = 0.0):
        self.prefill_engine = prefill_engine
        self.decode_engine = decode_engine
        self.request_timeout_ms = request_timeout_ms

    def _req(self, tokens: List[int], max_tokens: int, **extra: Any) -> dict:
        stop: Dict[str, Any] = {"max_tokens": max_tokens}
        if self.request_timeout_ms:
            stop["deadline_ms"] = self.request_timeout_ms
        return {
            "token_ids": list(tokens),
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": stop,
            **extra,
        }

    async def request(self, tokens: List[int], osl: int, t: float) -> Outcome:
        from dynamo_tpu.runtime.engine import Context

        out = Outcome(t=t, isl=len(tokens), osl=osl)
        t0 = time.monotonic()
        try:
            async for item in self.prefill_engine.generate(
                self._req(tokens, 1), Context()
            ):
                data = item.data if hasattr(item, "data") else item
                if isinstance(data, dict) and data.get("token_ids"):
                    if out.ttft_s is None:
                        out.ttft_s = time.monotonic() - t0
                if isinstance(data, dict) and data.get("finish_reason"):
                    break
            got: List[int] = []
            finish = None
            async for item in self.decode_engine.generate(
                self._req(tokens, osl, prefill_done=True), Context()
            ):
                data = item.data if hasattr(item, "data") else item
                if not isinstance(data, dict):
                    continue
                got.extend(data.get("token_ids") or ())
                if data.get("finish_reason"):
                    finish = data["finish_reason"]
                    break
            out.e2e_s = time.monotonic() - t0
            out.finish = finish
            out.tokens = len(got)
            expected = list(range(len(tokens), len(tokens) + osl))
            out.token_exact = got == expected[: len(got)] and (
                finish != "length" or len(got) == osl
            )
        except Exception as e:  # noqa: BLE001 — the harness counts, never masks
            out.error = f"{type(e).__name__}: {e}"
            out.e2e_s = time.monotonic() - t0
        return out


# --- the harness --------------------------------------------------------------
class TrafficHarness:
    """Seeded open-loop arrival process over a request path."""

    def __init__(
        self,
        path: DisaggPath,
        pattern: TrafficPattern,
        *,
        block_size: int = 16,
        prefix_groups: int = 4,
    ):
        self.path = path
        self.pattern = pattern
        self.prompts = PromptFactory(block_size=block_size, groups=prefix_groups)
        self.outcomes: List[Outcome] = []

    async def run(self) -> List[Outcome]:
        rng = random.Random(self.pattern.seed)
        start = time.monotonic()
        tasks: List[asyncio.Task] = []
        t = 0.0
        while True:
            rate = max(self.pattern.rate(t), 1e-3)
            t += rng.expovariate(rate)
            if t >= self.pattern.duration_s:
                break
            off = self.pattern.offered(t)
            tokens = self.prompts.make(rng, off.isl, off.prefix_ratio)
            now_rel = time.monotonic() - start
            if t > now_rel:
                await asyncio.sleep(t - now_rel)
            tasks.append(asyncio.create_task(self.path.request(tokens, off.osl, t)))
        if tasks:
            self.outcomes = list(await asyncio.gather(*tasks))
        return self.outcomes

    # --- aggregation -------------------------------------------------------
    def windows(self, window_s: float = 2.0, slo_ttft_ms: float = 0.0,
                slo_e2e_ms: float = 0.0) -> List[dict]:
        """SLO-attainment and goodput curves across the run, per window."""
        if not self.outcomes:
            return []
        n_win = max(1, math.ceil(self.pattern.duration_s / window_s))
        wins: List[dict] = []
        for w in range(n_win):
            lo, hi = w * window_s, (w + 1) * window_s
            rows = [o for o in self.outcomes if lo <= o.t < hi]
            done = [o for o in rows if o.completed]
            ttfts = sorted(o.ttft_s for o in done if o.ttft_s is not None)

            def pct(p: float) -> Optional[float]:
                if not ttfts:
                    return None
                return ttfts[min(int(p * len(ttfts)), len(ttfts) - 1)]

            attained = [
                o for o in done
                if (not slo_ttft_ms or (o.ttft_s or 0.0) * 1000.0 <= slo_ttft_ms)
                and (not slo_e2e_ms or (o.e2e_s or 0.0) * 1000.0 <= slo_e2e_ms)
            ]
            wins.append({
                "t": lo,
                "offered_rate": round(self.pattern.rate((lo + hi) / 2), 3),
                "sent": len(rows),
                "completed": len(done),
                "errors": sum(1 for o in rows if o.error is not None),
                "ttft_p50_ms": round(pct(0.50) * 1000, 1) if ttfts else None,
                "ttft_p99_ms": round(pct(0.99) * 1000, 1) if ttfts else None,
                "slo_attained": len(attained),
                "slo_attainment": round(len(attained) / len(done), 4) if done else None,
                "goodput_req_s": round(len(attained) / window_s, 3),
                "goodput_tok_s": round(sum(o.tokens for o in attained) / window_s, 1),
            })
        return wins

    def totals(self) -> dict:
        rows = self.outcomes
        done = [o for o in rows if o.completed]
        return {
            "requests": len(rows),
            "completed": len(done),
            "errors": sum(1 for o in rows if o.error is not None),
            "timeouts": sum(1 for o in rows if o.finish == "timeout"),
            "cancelled": sum(1 for o in rows if o.finish == "cancelled"),
            # Completed (surviving) requests whose token stream diverged
            # from the expected positions: MUST be zero under drains,
            # migrations, and injected crashes.
            "token_loss": sum(1 for o in done if not o.token_exact),
        }


# --- the closed-loop autoscale bench ------------------------------------------
@dataclass
class AutoscaleBenchConfig:
    pattern: TrafficPattern = field(default_factory=TrafficPattern)
    adjustment_interval_s: float = 1.5
    scrape_interval_s: float = 0.5
    scale_cooldown_s: float = 3.0
    min_prefill: int = 1
    max_prefill: int = 6
    min_decode: int = 1
    max_decode: int = 6
    slo_ttft_ms: float = 1500.0
    slo_tpot_ms: float = 120.0
    drain_timeout_s: float = 6.0
    utilization: float = 0.8
    # Chaos: armed the moment the first scale event lands (a crash DURING a
    # scale event); empty string disables.
    chaos_spec: str = '[{"site": "worker.step", "kind": "crash", "after": 3, "count": 1}]'
    chaos_seed: int = 0
    settle_s: float = 2.0  # post-pattern grace for stragglers

    def prefill_args(self):
        from dynamo_tpu.llm.mocker import MockEngineArgs

        # Prefill-tuned: compute-bound prompt processing dominates
        # (2 ms/token ⇒ ~500 tok/s/worker), token emission fast.
        return MockEngineArgs(
            prefill_base_ms=1.0, prefill_per_token_us=2000.0,
            itl_base_ms=2.0, itl_per_seq_ms=0.1, max_batch=16,
            num_blocks=512, token_rule="position",
            slo_ttft_ms=self.slo_ttft_ms, slo_tpot_ms=None,
        )

    def decode_args(self):
        from dynamo_tpu.llm.mocker import MockEngineArgs

        # Decode-tuned: bandwidth-bound steps (~45 ms at b4 ⇒ ~90 tok/s/
        # worker), prefill legs never land here (prefill_done).
        return MockEngineArgs(
            prefill_base_ms=0.5, prefill_per_token_us=200.0,
            itl_base_ms=40.0, itl_per_seq_ms=1.0, max_batch=4,
            num_blocks=512, token_rule="position",
            slo_ttft_ms=None, slo_tpot_ms=self.slo_tpot_ms,
        )


def capacity_oracle(cfg: AutoscaleBenchConfig, offered: Offered) -> Dict[str, int]:
    """Pool sizes the capacity model implies for the TRUE offered load —
    what the controller should converge to from observed signals alone."""
    from dynamo_tpu.planner.controller import MockerCapacityModel

    model = MockerCapacityModel(
        cfg.prefill_args(), decode_args=cfg.decode_args(), utilization=cfg.utilization
    )
    want = model.required(offered.rate, offered.isl, offered.osl)
    want["prefill"] = max(cfg.min_prefill, min(cfg.max_prefill, want["prefill"]))
    want["decode"] = max(cfg.min_decode, min(cfg.max_decode, want["decode"]))
    return want


async def run_autoscale_bench(cfg: Optional[AutoscaleBenchConfig] = None) -> dict:
    """Stand up the full autoscaling plane in-process, run the harness
    against it, and report the closed-loop curves."""
    from dynamo_tpu.llm.kv_router import KvPushRouter, KvRouterConfig
    from dynamo_tpu.llm.migration import Migration
    from dynamo_tpu.metrics_aggregator import MetricsAggregator
    from dynamo_tpu.planner.controller import (
        AutoscaleController,
        ControllerConfig,
        MockerCapacityModel,
    )
    from dynamo_tpu.planner.fleet import AutoscaleLoop, MockerFleet
    from dynamo_tpu.planner.observer import PrometheusObserver
    from dynamo_tpu.runtime import faults
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.health import SystemHealth, SystemStatusServer

    cfg = cfg or AutoscaleBenchConfig()
    drt = await DistributedRuntime.detached()
    server = None
    agg = None
    routers: List[Any] = []
    try:
        fleet = MockerFleet(
            drt, "autoscale",
            make_args=lambda c: cfg.prefill_args() if c == "prefill" else cfg.decode_args(),
            drain_timeout_s=cfg.drain_timeout_s,
        )
        for _ in range(cfg.min_prefill):
            await fleet.add_worker("prefill")
        for _ in range(cfg.min_decode):
            await fleet.add_worker("decode")

        controller = AutoscaleController(
            ControllerConfig(
                min_prefill=cfg.min_prefill, max_prefill=cfg.max_prefill,
                min_decode=cfg.min_decode, max_decode=cfg.max_decode,
                scale_cooldown_s=cfg.scale_cooldown_s,
                scale_up_stable_intervals=1, scale_down_stable_intervals=2,
                ttft_sla_s=cfg.slo_ttft_ms / 1000.0, tpot_sla_s=cfg.slo_tpot_ms / 1000.0,
                load_predictor="trend",
            ),
            MockerCapacityModel(
                cfg.prefill_args(), decode_args=cfg.decode_args(),
                utilization=cfg.utilization,
            ),
        )
        await fleet.serve_planner(controller)

        # Aggregator scrapes both pools + the planner; the observer reads
        # its real /metrics exposition over HTTP — the production loop.
        agg = MetricsAggregator(
            drt, "autoscale", "prefill", "generate",
            interval_s=cfg.scrape_interval_s,
            extra_endpoints=["autoscale/decode/generate", "autoscale/planner/control"],
        )
        await agg.start()
        health = SystemHealth()
        health.set_system_ready()
        server = SystemStatusServer(health, metrics=agg.registry)
        server.config.port = 0
        await server.start()
        observer = PrometheusObserver(f"http://127.0.0.1:{server.port}/metrics")

        prefill_client = await fleet.endpoint("prefill").client()
        decode_client = await fleet.endpoint("decode").client()
        await prefill_client.wait_for_instances(cfg.min_prefill, timeout=10)
        await decode_client.wait_for_instances(cfg.min_decode, timeout=10)
        prefill_router = await KvPushRouter.create(prefill_client, KvRouterConfig(block_size=16))
        decode_router = await KvPushRouter.create(decode_client, KvRouterConfig(block_size=16))
        routers = [prefill_router, decode_router]

        def router_stats() -> dict:
            merged: Dict[int, int] = {}
            for r in routers:
                for wid, n in r.stats()["cached_tokens_by_worker"].items():
                    merged[wid] = merged.get(wid, 0) + n
            return {"cached_tokens_by_worker": merged}

        loop = AutoscaleLoop(
            controller, fleet, observer.observe,
            interval_s=cfg.adjustment_interval_s, router_stats_fn=router_stats,
        )

        path = DisaggPath(
            Migration(3).attach(prefill_router), Migration(3).attach(decode_router)
        )
        harness = TrafficHarness(path, cfg.pattern)

        timeline: List[dict] = []
        chaos_armed_at: Optional[float] = None

        async def control() -> None:
            nonlocal chaos_armed_at
            start = time.monotonic()
            while time.monotonic() - start < cfg.pattern.duration_s + cfg.settle_s:
                await asyncio.sleep(cfg.adjustment_interval_s)
                decisions = await loop.step()
                t_rel = time.monotonic() - start
                timeline.append({
                    "t": round(t_rel, 2),
                    "prefill": fleet.size("prefill"),
                    "decode": fleet.size("decode"),
                    "targets": dict(controller._targets),
                    "drains_in_flight": {
                        c: fleet.drains_in_flight(c) for c in ("prefill", "decode")
                    },
                    "actions": [
                        {"pool": d.pool, "action": d.action, "count": d.count,
                         "victims": [f"{v:x}" for v in d.victims]}
                        for d in decisions if d.action != "hold"
                    ],
                })
                if (
                    cfg.chaos_spec
                    and chaos_armed_at is None
                    and any(d.action != "hold" for d in decisions)
                ):
                    # First scale event just landed: arm the chaos scenario
                    # NOW so the fault fires while the fleet is mid-change.
                    faults.arm_from_spec(cfg.chaos_spec, seed=cfg.chaos_seed)
                    chaos_armed_at = t_rel
                    logger.info("chaos armed at t=%.1fs (scale event in flight)", t_rel)

        control_task = asyncio.create_task(control())
        await harness.run()
        await asyncio.sleep(cfg.settle_s)
        control_task.cancel()
        try:
            await control_task
        except asyncio.CancelledError:
            pass

        chaos = {
            "armed_at_s": chaos_armed_at,
            "injections": faults.stats().get("faults_injected_total", 0),
            "log": [dict(r) for r in (faults.get_injector().log if faults.get_injector() else [])],
        }
        faults.disarm()

        final_offered = cfg.pattern.offered(cfg.pattern.duration_s)
        oracle = capacity_oracle(cfg, final_offered)
        final = {
            "prefill": fleet.size("prefill"),
            "decode": fleet.size("decode"),
            "oracle_prefill": oracle["prefill"],
            "oracle_decode": oracle["decode"],
            "converged": (
                abs(fleet.size("prefill") - oracle["prefill"]) <= 1
                and abs(fleet.size("decode") - oracle["decode"]) <= 1
            ),
        }
        peak_offered = max(
            (cfg.pattern.offered(w["t"]) for w in timeline or [{"t": 0.0}]),
            key=lambda o: o.rate, default=final_offered,
        ) if timeline else final_offered
        windows = harness.windows(
            window_s=max(cfg.adjustment_interval_s, 1.0), slo_ttft_ms=cfg.slo_ttft_ms
        )
        done = [o for o in harness.outcomes if o.completed]
        attained = sum(w["slo_attained"] for w in windows)
        report = {
            "pattern": asdict(cfg.pattern),
            "windows": windows,
            "timeline": timeline,
            "totals": harness.totals(),
            "slo_attainment": round(attained / len(done), 4) if done else None,
            "final": final,
            "peak_oracle": capacity_oracle(cfg, peak_offered),
            "max_pools": {
                "prefill": max((t["prefill"] for t in timeline), default=cfg.min_prefill),
                "decode": max((t["decode"] for t in timeline), default=cfg.min_decode),
            },
            "chaos": chaos,
            "planner": controller.to_stats(),
            "fleet": fleet.summary(),
        }
        for r in routers:
            await r.close()
        routers = []
        await fleet.shutdown()
        return report
    finally:
        faults.disarm()
        for r in routers:
            try:
                await r.close()
            except Exception:  # noqa: BLE001
                pass
        if agg is not None:
            await agg.stop()
        if server is not None:
            await server.stop()
        await drt.shutdown()


# --- elastic prefill/decode bench ---------------------------------------------
class ColocatedPath:
    """Single-leg request path: prompt AND decode on one worker (round-robin
    over the pool) — the pure co-located extreme of the elastic ladder."""

    def __init__(self, engines: List[Any], *, request_timeout_ms: float = 0.0):
        self.engines = list(engines)
        self.request_timeout_ms = request_timeout_ms
        self._rr = 0

    def _req(self, tokens: List[int], max_tokens: int, **extra: Any) -> dict:
        stop: Dict[str, Any] = {"max_tokens": max_tokens}
        if self.request_timeout_ms:
            stop["deadline_ms"] = self.request_timeout_ms
        return {
            "token_ids": list(tokens),
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": stop,
            **extra,
        }

    async def request(self, tokens: List[int], osl: int, t: float) -> Outcome:
        from dynamo_tpu.runtime.engine import Context

        eng = self.engines[self._rr % len(self.engines)]
        self._rr += 1
        return await _single_leg(eng, self._req(tokens, osl), tokens, osl, t)


async def _single_leg(engine, req: dict, tokens: List[int], osl: int, t: float) -> Outcome:
    """Run one co-located request (prefill + decode on ``engine``) and score
    it exactly like DisaggPath does: TTFT = first token, the position-rule
    stream must match the expected positions bit-for-bit."""
    from dynamo_tpu.runtime.engine import Context

    out = Outcome(t=t, isl=len(tokens), osl=osl)
    t0 = time.monotonic()
    got: List[int] = []
    finish = None
    try:
        async for item in engine.generate(req, Context()):
            data = item.data if hasattr(item, "data") else item
            if not isinstance(data, dict):
                continue
            if data.get("token_ids") and out.ttft_s is None:
                out.ttft_s = time.monotonic() - t0
            got.extend(data.get("token_ids") or ())
            if data.get("finish_reason"):
                finish = data["finish_reason"]
                break
        out.e2e_s = time.monotonic() - t0
        out.finish = finish
        out.tokens = len(got)
        expected = list(range(len(tokens), len(tokens) + osl))
        out.token_exact = got == expected[: len(got)] and (
            finish != "length" or len(got) == osl
        )
    except Exception as e:  # noqa: BLE001 — the harness counts, never masks
        out.error = f"{type(e).__name__}: {e}"
        out.e2e_s = time.monotonic() - t0
    return out


class ElasticPath(DisaggPath):
    """The elastic ladder over two mixed-capable workers: two-leg disagg by
    default (clean decode steps), degrading a request to a co-located single
    leg on whichever worker has slack when its preferred leg's worker is
    saturated — DEGRADE instead of queue. Saturation is judged from the
    worker's own scheduler state (waiting + running vs slots), the same
    signal the disagg handler's pool_load_probe scrapes."""

    def __init__(
        self,
        prefill_engine,
        decode_engine,
        *,
        prefill_saturated: Callable[[], bool],
        decode_saturated: Callable[[], bool],
        note_degrade: Optional[Callable[[str, str], None]] = None,
        request_timeout_ms: float = 0.0,
    ):
        super().__init__(prefill_engine, decode_engine, request_timeout_ms=request_timeout_ms)
        self.prefill_saturated = prefill_saturated
        self.decode_saturated = decode_saturated
        # (direction, target_worker) — target is who absorbs the degraded leg.
        self.note_degrade = note_degrade or (lambda d, tgt: None)
        self.degrades_to_decode = 0  # prefill pool saturated → co-locate on decode worker
        self.degrades_to_prefill = 0  # decode pool saturated → co-locate on prefill worker

    async def request(self, tokens: List[int], osl: int, t: float) -> Outcome:
        if self.prefill_saturated():
            self.degrades_to_decode += 1
            self.note_degrade("disagg_to_colocated", "decode")
            return await _single_leg(
                self.decode_engine, self._req(tokens, osl), tokens, osl, t
            )
        if self.decode_saturated():
            self.degrades_to_prefill += 1
            self.note_degrade("disagg_to_colocated", "prefill")
            return await _single_leg(
                self.prefill_engine, self._req(tokens, osl), tokens, osl, t
            )
        return await super().request(tokens, osl, t)


@dataclass
class ElasticBenchConfig:
    """Degrade-vs-queue: one shifting ISL/OSL mix offered to three fleets of
    IDENTICAL hardware (same worker count, same MockEngineArgs) that differ
    only in topology policy — pure disagg (static split, queue on
    saturation), pure co-located (mixed everywhere, constant interference),
    elastic (disagg + capacity dial + degradation ladder)."""

    pattern: TrafficPattern = field(default_factory=lambda: TrafficPattern(
        kind="ramp", duration_s=16.0, base_rate=4.0, peak_rate=4.0,
        # The mix flip: starts prefill-heavy (long prompts, short answers),
        # ends decode-heavy — prefill and decode demand cross mid-run.
        isl=224, isl_end=48, osl=6, osl_end=40,
        prefix_ratio=0.0, seed=0,
    ))
    slo_ttft_ms: float = 600.0
    slo_e2e_ms: float = 4000.0
    dial_interval_s: float = 1.0
    # Queue depth (waiting + running beyond slots) at which the elastic path
    # degrades instead of queueing.
    saturation_depth: int = 3
    settle_s: float = 3.0

    def worker_args(self):
        from dynamo_tpu.llm.mocker import MockEngineArgs

        # Mixed-capable: meaningful prefill cost (compute-bound prompts) AND
        # meaningful decode cost (bandwidth-bound steps), so the dial's
        # budget split moves real queues in both directions.
        return MockEngineArgs(
            prefill_base_ms=1.0, prefill_per_token_us=1500.0,
            itl_base_ms=30.0, itl_per_seq_ms=1.0,
            max_batch=4, max_prefill_chunk=256,
            num_blocks=768, token_rule="position",
            slo_ttft_ms=self.slo_ttft_ms, slo_tpot_ms=None,
        )


def _prefill_saturation_probe(engine, budget_ms: float) -> Callable[[], bool]:
    """Saturated = the pending prefill work already queued (tokens not yet
    computed, priced by the worker's own timing model) would push a new
    arrival's TTFT past ``budget_ms`` — the cost-model form of the disagg
    handler's pool_load_probe."""

    def probe() -> bool:
        pend = sum(
            max(s.prefill_span - s.computed, 0)
            for s in engine.waiting + engine.running
        )
        return engine.args.prefill_ms(pend) > budget_ms

    return probe


def _decode_saturation_probe(engine, depth: int) -> Callable[[], bool]:
    """Saturated = every decode slot is taken AND a queue is forming."""

    def probe() -> bool:
        backlog = len(engine.waiting) + len(engine.running)
        return backlog >= engine.args.max_batch + depth

    return probe


async def _run_elastic_scenario(cfg: ElasticBenchConfig, mode: str) -> dict:
    """One fleet, one mode, the shared pattern. Returns windows + totals."""
    from dynamo_tpu.llm.mocker import MockTpuEngine
    from dynamo_tpu.planner.controller import (
        AutoscaleController,
        ControllerConfig,
        MockerCapacityModel,
    )
    from dynamo_tpu.planner.planner_core import ObservedLoad

    args_a, args_b = cfg.worker_args(), cfg.worker_args()
    a, b = MockTpuEngine(args_a), MockTpuEngine(args_b)
    dial_timeline: List[dict] = []
    stop_dial = asyncio.Event()
    dial_task: Optional[asyncio.Task] = None

    if mode == "disagg":
        path: Any = DisaggPath(a, b)
    elif mode == "colocated":
        path = ColocatedPath([a, b])
    elif mode == "elastic":
        path = ElasticPath(
            a, b,
            prefill_saturated=_prefill_saturation_probe(a, cfg.slo_ttft_ms * 0.4),
            decode_saturated=_decode_saturation_probe(b, cfg.saturation_depth),
            note_degrade=lambda d, tgt: (b if tgt == "decode" else a).note_degrade(d),
        )
        controller = AutoscaleController(
            ControllerConfig(
                dial_deadband=0.02, dial_min_interval_s=cfg.dial_interval_s * 0.5,
            ),
            MockerCapacityModel(args_a, utilization=0.8),
        )

        async def actuate() -> None:
            # The planner ratio actuator driven by the offered curve: the
            # same decide_dial the AutoscaleLoop runs, fed the true mix
            # (the observer's job in the full-plane autoscale bench).
            start = time.monotonic()
            while not stop_dial.is_set():
                await asyncio.sleep(cfg.dial_interval_s)
                t_rel = time.monotonic() - start
                off = cfg.pattern.offered(min(t_rel, cfg.pattern.duration_s))
                load = ObservedLoad(
                    request_rate=off.rate, avg_isl=float(off.isl), avg_osl=float(off.osl)
                )
                d = controller.decide_dial(load, time.monotonic())
                if d is not None:
                    # Role-aware actuation: the dial only SHRINKS a budget
                    # (both sides clamp at the configured base), so each
                    # worker is dialed toward its role and never below the
                    # configured identity on the axis it serves.
                    applied_a = a.set_capacity_dial(max(d.fraction, 0.5))
                    applied_b = b.set_capacity_dial(min(d.fraction, 0.5))
                    dial_timeline.append({
                        "t": round(t_rel, 2), "fraction": round(d.fraction, 3),
                        "prefill_worker": applied_a, "decode_worker": applied_b,
                    })

        dial_task = asyncio.create_task(actuate())
    else:
        raise ValueError(f"unknown elastic bench mode {mode!r}")

    harness = TrafficHarness(path, cfg.pattern)
    try:
        await harness.run()
        await asyncio.sleep(cfg.settle_s)
    finally:
        stop_dial.set()
        if dial_task is not None:
            dial_task.cancel()
            try:
                await dial_task
            except asyncio.CancelledError:
                pass
        for eng in (a, b):
            stop = getattr(eng, "stop", None)
            if stop is not None:
                try:
                    await stop()
                except Exception:  # noqa: BLE001
                    pass

    windows = harness.windows(
        window_s=2.0, slo_ttft_ms=cfg.slo_ttft_ms, slo_e2e_ms=cfg.slo_e2e_ms
    )
    totals = harness.totals()
    done = [o for o in harness.outcomes if o.completed]
    attained = sum(w["slo_attained"] for w in windows)
    goodput_tok = sum(
        o.tokens for o in done
        if (o.ttft_s or 0.0) * 1000.0 <= cfg.slo_ttft_ms
        and (o.e2e_s or 0.0) * 1000.0 <= cfg.slo_e2e_ms
    )
    out = {
        "mode": mode,
        "windows": windows,
        "totals": totals,
        "slo_attainment": round(attained / len(done), 4) if done else 0.0,
        "goodput_tok_total": goodput_tok,
        "stats": {
            "a": {k: v for k, v in a.stats_handler().items()
                  if k.startswith(("elastic_", "degrade_"))},
            "b": {k: v for k, v in b.stats_handler().items()
                  if k.startswith(("elastic_", "degrade_"))},
        },
    }
    if mode == "elastic":
        out["dial_timeline"] = dial_timeline
        out["degrades"] = {
            "to_decode_worker": path.degrades_to_decode,
            "to_prefill_worker": path.degrades_to_prefill,
        }
    return out


async def run_elastic_bench(cfg: Optional[ElasticBenchConfig] = None) -> dict:
    """The ``elastic`` bench section: degrade-vs-queue TTFT/goodput curves
    under a shifting ISL/OSL mix. CI asserts the elastic fleet's SLO
    attainment AND goodput strictly dominate both static extremes, with
    zero token loss in every mode."""
    cfg = cfg or ElasticBenchConfig()
    scenarios: Dict[str, dict] = {}
    for mode in ("disagg", "colocated", "elastic"):
        scenarios[mode] = await _run_elastic_scenario(cfg, mode)
    el, dis, col = scenarios["elastic"], scenarios["disagg"], scenarios["colocated"]
    return {
        "pattern": asdict(cfg.pattern),
        "slo": {"ttft_ms": cfg.slo_ttft_ms, "e2e_ms": cfg.slo_e2e_ms},
        "scenarios": scenarios,
        "summary": {
            "slo_attainment": {m: scenarios[m]["slo_attainment"] for m in scenarios},
            "goodput_tok_total": {m: scenarios[m]["goodput_tok_total"] for m in scenarios},
            "token_loss": {m: scenarios[m]["totals"]["token_loss"] for m in scenarios},
            "errors": {m: scenarios[m]["totals"]["errors"] for m in scenarios},
            "degrades": el.get("degrades"),
            "dial_moves": len(el.get("dial_timeline") or ()),
            "elastic_dominates": (
                el["slo_attainment"] > dis["slo_attainment"]
                and el["slo_attainment"] > col["slo_attainment"]
                and el["goodput_tok_total"] > dis["goodput_tok_total"]
                and el["goodput_tok_total"] > col["goodput_tok_total"]
            ),
        },
    }


def main() -> None:
    p = argparse.ArgumentParser(description="mocker-fleet traffic harness / autoscale bench")
    p.add_argument("--pattern", choices=["diurnal", "flash", "ramp", "noisy_flat"],
                   default="diurnal")
    p.add_argument("--duration", type=float, default=30.0)
    p.add_argument("--base-rate", type=float, default=2.0)
    p.add_argument("--peak-rate", type=float, default=10.0)
    p.add_argument("--isl", type=int, default=96)
    p.add_argument("--isl-end", type=int, default=None)
    p.add_argument("--osl", type=int, default=16)
    p.add_argument("--osl-end", type=int, default=None)
    p.add_argument("--prefix-ratio", type=float, default=0.5)
    p.add_argument("--noise", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--adjustment-interval", type=float, default=1.5)
    p.add_argument("--scale-cooldown-s", type=float, default=3.0)
    p.add_argument("--no-chaos", action="store_true")
    p.add_argument("--out", default=None, help="write the report JSON here (default stdout)")
    args = p.parse_args()

    cfg = AutoscaleBenchConfig(
        pattern=TrafficPattern(
            kind=args.pattern, duration_s=args.duration,
            base_rate=args.base_rate, peak_rate=args.peak_rate,
            isl=args.isl, isl_end=args.isl_end, osl=args.osl, osl_end=args.osl_end,
            prefix_ratio=args.prefix_ratio, noise=args.noise, seed=args.seed,
        ),
        adjustment_interval_s=args.adjustment_interval,
        scale_cooldown_s=args.scale_cooldown_s,
        chaos_spec="" if args.no_chaos else AutoscaleBenchConfig.chaos_spec,
    )
    report = asyncio.run(run_autoscale_bench(cfg))
    text = json.dumps(report, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)


if __name__ == "__main__":
    main()
