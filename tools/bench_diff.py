"""Compare two bench rounds section-by-section with regression thresholds.

``bench.py`` emits one JSON object per round; the repo keeps the history as
``BENCH_r*.json`` wrappers (``{n, cmd, rc, tail, parsed}``). A fresh round is
only a number until it's placed against the previous one — and eyeballing
two 2000-char JSON blobs is how a 15% decode regression ships. This tool
makes the comparison mechanical:

    python tools/bench_diff.py BENCH_r04.json BENCH_r05.json
    python tools/bench_diff.py --latest            # two newest rounds in repo
    python tools/bench_diff.py old.json new.json --strict   # rc=1 on regression

Input tolerance (a diff tool that crashes on the history it must read is
useless): each input may be a raw bench output (``{metric, value, detail}``),
a round wrapper with ``parsed`` set, or a wrapper whose ``parsed`` is null —
there the ``tail`` is scanned for the final JSON line, and failing that, for
intact per-section sub-objects (``"observability": {...}``) recovered with
``raw_decode`` from the truncated fragment. Sections absent on either side
are reported as not-comparable, never as regressions.

Thresholds are per-metric, not global: throughput-style numbers (higher
better) regress on a relative drop, overhead/latency percentages (lower
better) regress on an absolute rise, and invariant booleans (``converged``,
``within_budget``, ``agreement.ok``, 0 post-warmup compiles) regress on any
true→false flip. Improvements are reported, not gated.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

# Sections worth recovering from a truncated tail fragment: every dict the
# bench's ``assemble`` places under detail.
_SECTION_KEYS = (
    "decode_attention", "prefill", "tpu_http_e2e", "http_e2e", "router_prefix",
    "prefix_reuse", "large_model", "mixed_admission", "observability",
    "device_truth", "guided_overhead", "decode_overlap", "autoscale", "elastic",
)


def _recover_sections(tail: str) -> Dict[str, Any]:
    """Pull intact ``"<section>": {...}`` sub-objects out of a truncated
    output fragment. The fragment's head is usually missing, so the full
    line never parses — but later sections often survive whole."""
    dec = json.JSONDecoder()
    out: Dict[str, Any] = {}
    for key in _SECTION_KEYS:
        for m in re.finditer(r'"%s"\s*:\s*\{' % re.escape(key), tail):
            try:
                obj, _ = dec.raw_decode(tail, m.end() - 1)
            except ValueError:
                continue
            if isinstance(obj, dict):
                out[key] = obj  # last occurrence wins (final summary line)
    # decode_sweep is a list of points.
    for m in re.finditer(r'"decode_sweep"\s*:\s*\[', tail):
        try:
            obj, _ = dec.raw_decode(tail, m.end() - 1)
        except ValueError:
            continue
        if isinstance(obj, list):
            out["decode_sweep"] = obj
    return out


def load_round(path: str) -> Tuple[Dict[str, Any], str]:
    """Returns (bench-result-shaped dict, provenance note). The result
    always has a ``detail`` dict; ``metric``/``value`` may be None when
    only fragments were recoverable."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and "detail" in obj:
        return obj, "raw"
    if isinstance(obj, dict) and "parsed" in obj:
        if isinstance(obj.get("parsed"), dict):
            return obj["parsed"], "wrapper"
        tail = obj.get("tail") or ""
        # Newest complete final line, if any line survived whole.
        final = None
        for line in tail.splitlines():
            line = line.strip()
            try:
                cand = json.loads(line)
            except ValueError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                final = cand
        if final is not None:
            return final, "tail-line"
        sections = _recover_sections(tail)
        return {"metric": None, "value": None, "detail": sections}, (
            f"tail-fragment ({len(sections)} sections recovered)"
        )
    raise ValueError(f"{path}: not a bench round (no 'detail' or 'parsed')")


# --------------------------------------------------------------------------
# comparison spec
# --------------------------------------------------------------------------

@dataclass
class Check:
    section: str
    label: str
    path: Tuple[str, ...]          # key path under detail
    direction: str                 # "higher" | "lower" | "flag"
    rel_tol: float = 0.10          # relative drop allowed (higher-better)
    abs_tol: float = 0.0           # absolute rise allowed (lower-better)


CHECKS: List[Check] = [
    Check("observability", "tracing overhead %", ("observability", "overhead_pct"),
          "lower", abs_tol=1.0),
    Check("observability", "within ≤2% budget", ("observability", "within_budget"),
          "flag"),
    Check("observability", "post-warmup compiles = 0",
          ("observability", "compiles_after_warmup"), "lower", abs_tol=0.0),
    Check("guided_overhead", "guided overhead %", ("guided_overhead", "overhead_pct"),
          "lower", abs_tol=1.5),
    Check("prefix_reuse", "prefix-reuse speedup", ("prefix_reuse", "speedup"),
          "higher", rel_tol=0.15),
    Check("autoscale", "SLO attainment", ("autoscale", "slo_attainment"),
          "higher", rel_tol=0.10),
    Check("autoscale", "converged on oracle", ("autoscale", "converged"), "flag"),
    Check("device_truth", "measured/modeled agreement",
          ("device_truth", "agreement", "ok"), "flag"),
    Check("device_truth", "measured-vs-modeled MFU rel err",
          ("device_truth", "agreement", "mfu_rel_err"), "lower", abs_tol=0.02),
    Check("http_e2e", "http e2e tok/s", ("http_e2e", "tok_s"),
          "higher", rel_tol=0.15),
    Check("tpu_http_e2e", "serving tok/s", ("tpu_http_e2e", "tok_s"),
          "higher", rel_tol=0.15),
]


def _dig(detail: Dict[str, Any], path: Tuple[str, ...]) -> Any:
    cur: Any = detail
    for key in path:
        if not isinstance(cur, dict):
            return None
        cur = cur.get(key)
        # autoscale round shape: asserts live under a "summary" sub-dict.
        if cur is None and isinstance(detail.get(path[0]), dict) and key != path[0]:
            parent = detail[path[0]].get("summary")
            if isinstance(parent, dict) and key in parent:
                cur = parent[key]
    return cur


def _decode_points(detail: Dict[str, Any]) -> Dict[Tuple[int, int], float]:
    out: Dict[Tuple[int, int], float] = {}
    for p in detail.get("decode_sweep") or []:
        if isinstance(p, dict) and "batch" in p and "tok_s_per_user" in p:
            out[(p["batch"], p.get("ctx", 0))] = float(p["tok_s_per_user"])
    return out


def compare(old: Dict[str, Any], new: Dict[str, Any]) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    od, nd = old.get("detail") or {}, new.get("detail") or {}

    # Headline metric, when both rounds carry one on the same axis.
    if (old.get("value") is not None and new.get("value") is not None
            and old.get("metric") == new.get("metric")):
        ov, nv = float(old["value"]), float(new["value"])
        drop = (ov - nv) / ov if ov else 0.0
        rows.append({
            "section": "headline", "label": old["metric"], "old": ov, "new": nv,
            "delta_pct": round(100.0 * (nv - ov) / ov, 2) if ov else None,
            "verdict": "regression" if drop > 0.10 else
                       ("improved" if nv > ov else "ok"),
        })

    # Decode sweep: per (batch, ctx) point, 10% relative drop threshold.
    op, np_ = _decode_points(od), _decode_points(nd)
    for key in sorted(set(op) & set(np_)):
        ov, nv = op[key], np_[key]
        drop = (ov - nv) / ov if ov else 0.0
        rows.append({
            "section": "decode_sweep", "label": f"b{key[0]} ctx{key[1]} tok/s/user",
            "old": ov, "new": nv,
            "delta_pct": round(100.0 * (nv - ov) / ov, 2) if ov else None,
            "verdict": "regression" if drop > 0.10 else
                       ("improved" if nv > ov else "ok"),
        })

    for c in CHECKS:
        ov, nv = _dig(od, c.path), _dig(nd, c.path)
        if ov is None or nv is None:
            rows.append({"section": c.section, "label": c.label,
                         "old": ov, "new": nv, "delta_pct": None,
                         "verdict": "not-comparable"})
            continue
        if c.direction == "flag":
            ok_old, ok_new = bool(ov), bool(nv)
            rows.append({"section": c.section, "label": c.label,
                         "old": ok_old, "new": ok_new, "delta_pct": None,
                         "verdict": "regression" if (ok_old and not ok_new)
                         else ("improved" if (not ok_old and ok_new) else "ok")})
            continue
        ov, nv = float(ov), float(nv)
        delta = round(100.0 * (nv - ov) / ov, 2) if ov else None
        if c.direction == "higher":
            drop = (ov - nv) / ov if ov else 0.0
            verdict = ("regression" if drop > c.rel_tol
                       else ("improved" if nv > ov else "ok"))
        else:  # lower-better: absolute rise beyond tolerance regresses
            verdict = ("regression" if nv - ov > c.abs_tol
                       else ("improved" if nv < ov else "ok"))
        rows.append({"section": c.section, "label": c.label, "old": ov,
                     "new": nv, "delta_pct": delta, "verdict": verdict})
    return rows


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "yes" if v else "no"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("rounds", nargs="*", help="OLD.json NEW.json")
    ap.add_argument("--latest", action="store_true",
                    help="compare the two newest BENCH_r*.json in the repo root")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 if any section regressed")
    args = ap.parse_args(argv)

    if args.latest:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rounds = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
        if len(rounds) < 2:
            print("bench_diff: fewer than two BENCH_r*.json rounds", file=sys.stderr)
            return 2
        paths = rounds[-2:]
    elif len(args.rounds) == 2:
        paths = args.rounds
    else:
        ap.error("provide OLD.json NEW.json, or --latest")
        return 2

    (old, old_src), (new, new_src) = load_round(paths[0]), load_round(paths[1])
    rows = compare(old, new)
    regressions = [r for r in rows if r["verdict"] == "regression"]

    if args.json:
        print(json.dumps({
            "old": {"path": paths[0], "source": old_src},
            "new": {"path": paths[1], "source": new_src},
            "rows": rows, "regressions": len(regressions),
        }, indent=1))
    else:
        print(f"bench_diff: {os.path.basename(paths[0])} ({old_src}) -> "
              f"{os.path.basename(paths[1])} ({new_src})")
        width = max((len(r["label"]) for r in rows), default=10)
        for r in rows:
            d = f"{r['delta_pct']:+.2f}%" if r["delta_pct"] is not None else "     "
            print(f"  [{r['verdict']:>14}] {r['label']:<{width}}  "
                  f"{_fmt(r['old'])} -> {_fmt(r['new'])}  {d}")
        comparable = [r for r in rows if r["verdict"] != "not-comparable"]
        print(f"  {len(comparable)} comparable, {len(regressions)} regression(s), "
              f"{sum(1 for r in rows if r['verdict'] == 'improved')} improved")
    if regressions and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
