"""Isolate the paged-KV cache cost in the decode step.

Variants (all with the real weights scan + lm_head):
- noscatter_nokernel : no cache write, no attention read (≈ no_attn floor)
- scatter_only       : cache write into stacked [L,...] carry, no read
- kernel_noscatter   : kernel attention read, no cache write
- kernel_full        : current full path (scatter + kernel)
- list_full_gather   : per-layer cache LIST (unrolled loop), scatter + gather
- list_full_kernel   : per-layer cache LIST (unrolled loop), scatter + kernel
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.models import llama


def bench_step(step, args, donate_ids, iters=50):
    """step(*args) -> (logits, k, v) with k,v donated and threaded."""
    args = list(args)
    out = step(*args)
    jax.block_until_ready(out)
    for slot, res in zip(donate_ids, out[1:]):
        args[slot] = res
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(*args)
        logits = out[0]
        for slot, res in zip(donate_ids, out[1:]):
            args[slot] = res
    jax.block_until_ready(logits)
    return (time.perf_counter() - t0) / iters * 1000


def main():
    model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    B = int(os.environ.get("BENCH_BATCH", "8"))
    ctx = int(os.environ.get("BENCH_CTX", "1024"))
    cfg = get_config(model).replace(max_seq_len=2048)
    c = cfg
    num_blocks = B * (ctx // cfg.block_size + 4) + 8
    L = cfg.num_layers

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    kshape = (L, num_blocks, cfg.block_size, cfg.num_kv_heads, cfg.head_dim)
    k_cache = jnp.zeros(kshape, dtype=jnp.bfloat16)
    v_cache = jnp.zeros(kshape, dtype=jnp.bfloat16)

    needed = (ctx + 64) // cfg.block_size
    width = min((needed + 15) // 16 * 16, cfg.max_seq_len // cfg.block_size)
    tables = np.zeros((B, width), dtype=np.int32)
    for i in range(B):
        tables[i, :needed] = (np.arange(needed) + 1 + i * needed) % (num_blocks - 1) + 1
    tables = jnp.asarray(tables)
    active = jnp.ones((B,), dtype=bool)
    toks = jnp.zeros((B,), dtype=jnp.int32)
    pos = jnp.full((B,), ctx, dtype=jnp.int32)

    def make_scan_variant(do_scatter: bool, attn: str):
        def step(p, kc, vc, t, po, tbl):
            h = p["embed"].at[t].get(mode="clip")
            tgt_blocks, tgt_offs, mask = llama.decode_targets(po, tbl, active, c.block_size)
            kv_lens = jnp.where(active, po + 1, 0)

            def layer_fn(carry, xs):
                h, kc, vc = carry
                lp, l = xs
                x = llama.rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
                q = (x @ lp["wq"]).reshape(B, 1, c.num_heads, c.head_dim)
                k = (x @ lp["wk"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
                v = (x @ lp["wv"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
                q = llama.apply_rope(q, po[:, None], c.rope_theta)[:, 0]
                k = llama.apply_rope(k, po[:, None], c.rope_theta)[:, 0]
                v = v[:, 0]
                if do_scatter:
                    kc = kc.at[l, tgt_blocks, tgt_offs].set(k)
                    vc = vc.at[l, tgt_blocks, tgt_offs].set(v)
                kl = lax.dynamic_index_in_dim(kc, l, 0, keepdims=False)
                vl = lax.dynamic_index_in_dim(vc, l, 0, keepdims=False)
                if attn == "gather":
                    ctxlen = tbl.shape[1] * c.block_size
                    k_ctx = kl[tbl].reshape(B, ctxlen, c.num_kv_heads, c.head_dim)
                    v_ctx = vl[tbl].reshape(B, ctxlen, c.num_kv_heads, c.head_dim)
                    a = jax.vmap(lambda qb, kb, vb, mb: llama._attend(qb[None], kb, vb, mb[None], c)[0])(
                        q, k_ctx, v_ctx, mask)
                else:
                    a = q
                h = h + a.reshape(B, c.q_size) @ lp["wo"]
                x = llama.rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
                h = h + llama._mlp(x, lp, c)
                return (h, kc, vc), None

            (h, kc, vc), _ = lax.scan(layer_fn, (h, kc, vc),
                                      (p["layers"], jnp.arange(c.num_layers, dtype=jnp.int32)))
            h = llama.rms_norm(h, p["final_norm"], c.rms_norm_eps)
            logits = h @ p["embed"].T
            return logits.astype(jnp.float32), kc, vc

        return jax.jit(step, donate_argnums=(1, 2))

    for name, (scat, attn) in {
        "noscatter_noattn": (False, "none"),
        "scatter_only": (True, "none"),
        "kernel_noscatter": (False, "kernel"),
        "kernel_full": (True, "kernel"),
        "gather_full": (True, "gather"),
    }.items():
        step = make_scan_variant(scat, attn)
        ms = bench_step(step, (params, jnp.copy(k_cache), jnp.copy(v_cache), toks, pos, tables), (1, 2))
        print(f"{name:18s}: {ms:7.3f} ms")

    # --- per-layer LIST cache, unrolled python loop ---
    k_list = [jnp.zeros(kshape[1:], dtype=jnp.bfloat16) for _ in range(L)]
    v_list = [jnp.zeros(kshape[1:], dtype=jnp.bfloat16) for _ in range(L)]

    def make_list_variant(attn: str):
        def step(p, ks, vs, t, po, tbl):
            h = p["embed"].at[t].get(mode="clip")
            tgt_blocks, tgt_offs, mask = llama.decode_targets(po, tbl, active, c.block_size)
            kv_lens = jnp.where(active, po + 1, 0)
            ks_out, vs_out = [], []
            for l in range(L):
                lp = {k2: v2[l] for k2, v2 in p["layers"].items()}
                x = llama.rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
                q = (x @ lp["wq"]).reshape(B, 1, c.num_heads, c.head_dim)
                k = (x @ lp["wk"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
                v = (x @ lp["wv"]).reshape(B, 1, c.num_kv_heads, c.head_dim)
                q = llama.apply_rope(q, po[:, None], c.rope_theta)[:, 0]
                k = llama.apply_rope(k, po[:, None], c.rope_theta)[:, 0]
                v = v[:, 0]
                kl = ks[l].at[tgt_blocks, tgt_offs].set(k)
                vl = vs[l].at[tgt_blocks, tgt_offs].set(v)
                ks_out.append(kl)
                vs_out.append(vl)
                if attn == "gather":
                    ctxlen = tbl.shape[1] * c.block_size
                    k_ctx = kl[tbl].reshape(B, ctxlen, c.num_kv_heads, c.head_dim)
                    v_ctx = vl[tbl].reshape(B, ctxlen, c.num_kv_heads, c.head_dim)
                    a = jax.vmap(lambda qb, kb, vb, mb: llama._attend(qb[None], kb, vb, mb[None], c)[0])(
                        q, k_ctx, v_ctx, mask)
                h = h + a.reshape(B, c.q_size) @ lp["wo"]
                x = llama.rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
                h = h + llama._mlp(x, lp, c)
            h = llama.rms_norm(h, p["final_norm"], c.rms_norm_eps)
            logits = h @ p["embed"].T
            return (logits.astype(jnp.float32), ks_out, vs_out)

        return jax.jit(step, donate_argnums=(1, 2))

    for name, attn in {"list_kernel": "kernel", "list_gather": "gather"}.items():
        step = make_list_variant(attn)
        ks = [jnp.copy(x) for x in k_list]
        vs = [jnp.copy(x) for x in v_list]
        out = step(params, ks, vs, toks, pos, tables)
        ks, vs = out[1], out[2]
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        iters = 50
        for _ in range(iters):
            logits, ks, vs = step(params, ks, vs, toks, pos, tables)
        jax.block_until_ready(logits)
        ms = (time.perf_counter() - t0) / iters * 1000
        print(f"{name:18s}: {ms:7.3f} ms")


if __name__ == "__main__":
    main()
