"""A/B the prefill attention implementations on the real chip.

Usage: python tools/bench_prefill_impl.py [model] [prompt_len]
Times one full prefill dispatch (cache donated per call, so the axon
tunnel's duplicate-execution cache cannot fake results) for the XLA path
vs the Pallas flash path, at table widths the scheduler would pass.
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama

model = sys.argv[1] if len(sys.argv) > 1 else "llama-3.2-1b"
prompt_len = int(sys.argv[2]) if len(sys.argv) > 2 else 2048

cfg = get_config(model).replace(max_seq_len=max(4096, prompt_len + 512))
params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
pbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
flops = 2 * (pbytes / 2) * prompt_len

num_blocks = prompt_len // cfg.block_size + 8
toks = jnp.arange(prompt_len, dtype=jnp.int32) % 1000

# Table width: power-of-two bucket covering the prompt (what the scheduler
# passes) — NOT max_blocks_per_seq.
w = 16
while w < prompt_len // cfg.block_size + 1:
    w *= 2
table = jnp.asarray(np.pad(np.arange(1, num_blocks, dtype=np.int32), (0, max(0, w - num_blocks + 1)))[:w])


def run(use_flash, label):
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    fn = jax.jit(
        lambda p, k, v, t: llama.prefill(
            p, cfg, k, v, t, jnp.int32(prompt_len), jnp.int32(0), table,
            use_flash=use_flash, has_prefix=False,
        ),
        donate_argnums=(1, 2),
    )
    k, v = cache.k, cache.v
    logits, k, v = fn(params, k, v, toks)
    np.asarray(logits[:4])  # real sync (block_until_ready is unreliable over axon)
    iters = 16
    t0 = time.perf_counter()
    for _ in range(iters):
        logits, k, v = fn(params, k, v, toks)
    np.asarray(logits[:4])
    dt = (time.perf_counter() - t0) / iters
    print(f"{label}: {dt*1e3:.2f} ms  ({prompt_len/dt:.0f} tok/s, mfu {flops/dt/1e12/197*100:.1f}%)")
    return dt


run(False, "xla  (pow2 table)")
run(True, "flash(pow2 table)")
