"""A/B decode attention backends on the real chip.

Usage: python tools/bench_decode_impl.py [model] [ctx]
Times multi-step-window decode (bench.py methodology: donated cache, real
host sync) for the gather decode path across batch sizes. (The Pallas
paged kernel this A/B'd against was deleted in r4 — it lost everywhere;
see ModelConfig.attention_impl.)
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama

model = sys.argv[1] if len(sys.argv) > 1 else "llama-3.2-1b"
ctx_len = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
window = 16
steps = 128

HBM_GBPS = 856.0  # measured copy roofline on this chip (tools probe)

base = get_config(model).replace(max_seq_len=max(4096, ctx_len + 512))
params = llama.init_params(base, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
pbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))


def run(impl, batch):
    cfg = base.replace(attention_impl=impl)
    num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    needed = (ctx_len + steps + 1 + cfg.block_size - 1) // cfg.block_size
    w = 4
    while w < needed:
        w *= 2
    tables = jnp.tile(jnp.arange(1, w + 1, dtype=jnp.int32)[None, :], (batch, 1))
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), dtype=bool)
    zf = jnp.zeros((batch,), jnp.float32)
    zi = jnp.zeros((batch,), jnp.int32)
    of = jnp.ones((batch,), jnp.float32)

    fn = jax.jit(
        lambda p, k, v, t, pos, key: llama.decode_multi(
            p, cfg, k, v, t, pos, tables, active, zf, zi, of, key, window
        ),
        donate_argnums=(1, 2),
    )
    toks = jnp.zeros((batch,), dtype=jnp.int32)
    pos = jnp.full((batch,), ctx_len, dtype=jnp.int32)
    k, v = cache.k, cache.v
    out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(0))
    np.asarray(out)  # real sync
    n_windows = max(1, steps // window)
    t0 = time.perf_counter()
    for i in range(n_windows):
        out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(i))
    np.asarray(out)
    dt = (time.perf_counter() - t0) / (n_windows * window)
    kv_bytes = 2 * cfg.num_layers * ctx_len * cfg.num_kv_heads * cfg.head_dim * 2 * batch
    gbps = (pbytes + kv_bytes) / dt / 1e9
    print(
        f"{impl:12s} b{batch:2d}: {dt*1e3:7.3f} ms/step  {batch/dt:7.0f} tok/s/chip  "
        f"{gbps:5.0f} GB/s ({100*gbps/HBM_GBPS:.1f}% roofline)"
    )


for batch in (8, 16, 32):
    run("gather", batch)
