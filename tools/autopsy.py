#!/usr/bin/env python
"""Incident / request autopsy: join traces, step rings, and digests into a
"why was this slow" attribution report.

Two modes over the same evidence:

- **Incident (window) mode** — given an incident bundle written by
  ``runtime/incidents.py``, rank every detector signal by how far it sits
  above the baseline it was judged against and attribute the incident to
  the slow-path component with the strongest evidence (queue wait vs
  prefill vs decode vs host gap vs mid-traffic compile vs stall), with the
  digest windows and the recent-step ring as supporting exhibits.
- **Request mode** (``--request <trace-id>``) — given trace records (JSONL
  files and/or a bundle's trace ring), reconstruct one request's phase
  breakdown from its lifecycle events (queued → admitted → first_token →
  finish) and report where its time went, what interfered (preemptions,
  disagg KV hops, mixed-step rides), and — when digests are available —
  where each phase sits against the fleet percentiles.

Usage::

    python tools/autopsy.py incident_0001_queue_wait_p99.json
    python tools/autopsy.py trace.jsonl --request <trace-id>
    python tools/autopsy.py incident_0001_*.json --request <trace-id> --json

Bundles and JSONL files mix freely on the command line; bundle trace rings
and file records merge into one record set.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.runtime.incidents import BUNDLE_SCHEMA
from dynamo_tpu.runtime.telemetry import LatencyDigest
from dynamo_tpu.runtime.tracing import read_trace_file

# Detector signal → the slow-path component it is evidence for.
SIGNAL_PHASE = {
    "queue_wait_p99": "queue_wait",
    "ttft_p99": "prefill",
    "tpot_p99": "decode",
    "host_gap": "decode_host_gap",
    "post_warmup_compile": "compile",
    "engine_stall": "stall",
}


# --- input loading -----------------------------------------------------------

def load_bundle(path: str) -> Optional[dict]:
    """Parse ``path`` as an incident bundle; None when it is not one (a
    JSONL trace file, a truncated write, ...)."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(obj, dict) and obj.get("schema") == BUNDLE_SCHEMA:
        return obj
    return None


def load_inputs(paths: List[str]) -> Tuple[List[dict], List[dict]]:
    """(bundles, trace_records) from a mixed list of bundle and JSONL
    paths. Bundle trace rings fold into the record set."""
    bundles: List[dict] = []
    records: List[dict] = []
    for path in paths:
        bundle = load_bundle(path)
        if bundle is not None:
            bundles.append(bundle)
            records.extend(r for r in bundle.get("trace_ring") or [] if isinstance(r, dict))
        else:
            records.extend(read_trace_file(path))
    return bundles, records


def _digest(bundle: Optional[dict], name: str) -> Optional[LatencyDigest]:
    """The bundle's WINDOW digest for one stream (the distribution at
    capture time), or None."""
    if bundle is None:
        return None
    wire = ((bundle.get("stats") or {}).get("digests") or {}).get(name)
    if not isinstance(wire, dict) or "window" not in wire:
        return None
    try:
        return LatencyDigest.from_wire(wire["window"])
    except (TypeError, ValueError, KeyError):
        return None


# --- incident (window) attribution -------------------------------------------

def incident_report(bundle: dict) -> dict:
    """Attribute one incident bundle to a slow-path component.

    Discrete signals (a mid-traffic compile, a stall transition) are
    categorical evidence and win outright when they fired. Continuous
    signals rank by ``value / baseline`` — how far the signal sits above
    the trailing normal the detector was tracking — so a 1500× queue-wait
    excursion beats the 80× TTFT jump it caused downstream."""
    detector = bundle.get("detector") or {}
    values: Dict[str, float] = detector.get("last_values") or {}
    baselines: Dict[str, float] = detector.get("baselines") or {}
    stats = bundle.get("stats") or {}
    reason = bundle.get("reason") or "?"

    ratios: Dict[str, float] = {}
    for signal in ("queue_wait_p99", "ttft_p99", "tpot_p99", "host_gap"):
        v, b = values.get(signal), baselines.get(signal)
        if v is None or b is None or b <= 0:
            continue
        ratios[signal] = v / b

    evidence: List[str] = []
    if reason == "engine_stall" or float(stats.get("engine_stalled", 0.0) or 0.0):
        attribution = "stall"
        evidence.append("stall watchdog: step loop wedged with work queued")
    elif reason == "post_warmup_compile":
        attribution = "compile"
        evidence.append(
            f"XLA compiled mid-traffic: compiles_after_warmup_total="
            f"{stats.get('compiles_after_warmup_total')}"
        )
    elif ratios:
        top = max(ratios, key=lambda s: ratios[s])
        attribution = SIGNAL_PHASE[top]
        for s in sorted(ratios, key=lambda s: -ratios[s]):
            evidence.append(
                f"{s}: {values[s] * 1000:.2f} ms vs baseline "
                f"{baselines[s] * 1000:.2f} ms ({ratios[s]:.1f}x)"
            )
    else:
        attribution = SIGNAL_PHASE.get(reason, reason)
        evidence.append("no continuous-signal evidence in bundle; attributed by trigger reason")

    # Supporting exhibits: digest percentiles + step-ring summary.
    digests = {}
    for name in ("queue_wait", "ttft", "tpot", "prefill_step", "decode_step", "mixed_step"):
        d = _digest(bundle, name)
        if d is not None and d.count:
            p50, p99 = d.quantile(0.5), d.quantile(0.99)
            digests[name] = {
                "count": d.count,
                "p50_ms": round(1000 * p50, 3),
                "p99_ms": round(1000 * p99, 3),
                "max_ms": round(1000 * d.max, 3),
            }
    flight = bundle.get("flight") or {}
    steps = flight.get("recent_steps") or []
    phases: Dict[str, int] = {}
    for s in steps:
        phases[s.get("phase", "?")] = phases.get(s.get("phase", "?"), 0) + 1

    return {
        "mode": "incident",
        "reason": reason,
        "ts": bundle.get("ts"),
        "detail": bundle.get("detail"),
        "attribution": attribution,
        "signal_ratios": {k: round(v, 2) for k, v in sorted(ratios.items(), key=lambda kv: -kv[1])},
        "evidence": evidence,
        "digests": digests,
        "recent_steps": {
            "count": len(steps),
            "by_phase": phases,
            "host_gap_p99_ms": round(1000 * float((flight.get("host_gap") or {}).get("p99_s") or 0.0), 3),
        },
        "compiles_after_warmup": stats.get("compiles_after_warmup_total"),
        "running": len((bundle.get("debug_state") or {}).get("running") or []),
        "waiting": len((bundle.get("debug_state") or {}).get("waiting") or []),
    }


# --- request attribution ------------------------------------------------------

def request_report(records: List[dict], trace_id: str,
                   bundle: Optional[dict] = None) -> dict:
    """Phase breakdown + attribution for one request's trace records."""
    recs = [r for r in records if r.get("trace_id") == trace_id
            and isinstance(r.get("ts"), (int, float))]
    if not recs:
        return {"mode": "request", "trace_id": trace_id,
                "error": "no records for this trace id"}
    recs.sort(key=lambda r: r["ts"])

    def first_event(name: str) -> Optional[dict]:
        return next((r for r in recs if r.get("name") == name), None)

    def attr(rec: Optional[dict], key: str):
        return (rec or {}).get("attrs", {}).get(key)

    queued = first_event("queued")
    first_token = first_event("first_token")
    finish = first_event("finish")
    t0 = recs[0]["ts"]
    t1 = max(r["ts"] + (r.get("dur_s") or 0.0) for r in recs)

    phases: Dict[str, float] = {}
    ttft_s = attr(first_token, "ttft_s")
    queue_s = attr(first_event("admitted"), "queue_s")
    if queue_s is None and queued is not None and first_event("admitted") is not None:
        queue_s = max(0.0, first_event("admitted")["ts"] - queued["ts"])
    if queue_s is not None:
        phases["queue_wait"] = float(queue_s)
    if ttft_s is not None:
        phases["prefill"] = max(0.0, float(ttft_s) - float(queue_s or 0.0))
    elif first_token is not None and queued is not None:
        phases["prefill"] = max(
            0.0, first_token["ts"] - queued["ts"] - float(queue_s or 0.0)
        )
    if finish is not None and first_token is not None:
        phases["decode"] = max(0.0, finish["ts"] - first_token["ts"])

    # Interference modifiers: not wall-time phases, but the "what else
    # happened to this request" column of the report.
    modifiers: List[str] = []
    preemptions = attr(finish, "preemptions")
    if preemptions:
        modifiers.append(f"preempted {preemptions}x (KV recomputed on resume)")
    n_disagg = sum(1 for r in recs if "disagg" in (r.get("name") or ""))
    if n_disagg:
        modifiers.append(f"disagg KV hop ({n_disagg} transfer events)")
    n_rides = sum(1 for r in recs if r.get("name") == "mixed_ride")
    if n_rides:
        modifiers.append(f"prefill rode {n_rides} mixed decode steps")
    cached = attr(first_token, "cached_tokens")
    if cached:
        modifiers.append(f"{cached} prompt tokens served from prefix cache")

    total = sum(phases.values()) or max(t1 - t0, 1e-9)
    attribution = max(phases, key=lambda p: phases[p]) if phases else "unknown"

    # Fleet context: where does this request sit in the capture-time
    # distribution of each phase?
    fleet: Dict[str, str] = {}
    for name, value in (("queue_wait", queue_s), ("ttft", ttft_s)):
        d = _digest(bundle, name)
        if d is not None and d.count and value is not None:
            fleet[name] = f"p{100.0 * d.rank(float(value)):.1f} of {d.count} in window"

    return {
        "mode": "request",
        "trace_id": trace_id,
        "records": len(recs),
        "total_ms": round(1000 * (t1 - t0), 3),
        "attribution": attribution,
        "phases_ms": {k: round(1000 * v, 3) for k, v in phases.items()},
        "phase_shares": {k: round(v / total, 4) for k, v in phases.items()},
        "modifiers": modifiers,
        "fleet_context": fleet,
        "finish_reason": attr(finish, "reason"),
        "output_tokens": attr(finish, "output_tokens"),
    }


# --- tenant attribution -------------------------------------------------------

# Incident attribution → the ledger dimension that explains "who did it".
_TENANT_DIMENSION = {
    "queue_wait": "queue_seconds",
    "prefill": "device_seconds",
    "decode": "device_seconds",
    "decode_host_gap": "device_seconds",
    "compile": "device_seconds",
    "stall": "device_seconds",
}


def tenant_report(bundle: dict) -> dict:
    """Attribute an incident to tenants: join the bundle's tenant-ledger
    evidence (runtime/ledger.py snapshot) with the window attribution, so
    the report can say e.g. "queue_wait spike is 84% tenant X"."""
    ledger = (bundle.get("evidence") or {}).get("tenant_ledger")
    if not isinstance(ledger, dict) or "device_seconds" not in ledger:
        # Older bundles (or a dead probe): fall back to the raw sketch wire
        # riding the captured stats scrape.
        wire = (bundle.get("stats") or {}).get("tenant_ledger")
        if isinstance(wire, dict):
            from dynamo_tpu.runtime.ledger import attribute

            ledger = attribute(wire)
        else:
            return {"mode": "tenant",
                    "error": "bundle carries no tenant ledger evidence"}

    base = incident_report(bundle)
    dim = _TENANT_DIMENSION.get(base["attribution"], "device_seconds")
    ranked = (ledger.get(dim) or {}).get("tenants") or []
    headline = None
    if ranked:
        top = ranked[0]
        headline = (f"{base['reason']}: {dim.replace('_', ' ')} is "
                    f"{100 * top['share']:.0f}% tenant '{top['tenant']}'")
    return {
        "mode": "tenant",
        "reason": base["reason"],
        "ts": bundle.get("ts"),
        "attribution": base["attribution"],
        "dimension": dim,
        "headline": headline,
        "bills": ledger.get("bills"),
        "ledger": {k: ledger.get(k) for k in
                   ("device_seconds", "kv_block_seconds", "queue_seconds")},
        "slo": ledger.get("slo") or {},
    }


# --- rendering ---------------------------------------------------------------

def render(report: dict, out=sys.stdout) -> None:
    mode = report.get("mode")
    if report.get("error"):
        out.write(f"autopsy: {report['error']}\n")
        return
    if mode == "tenant":
        out.write(f"incident: {report['reason']}  (ts {report.get('ts')})\n")
        out.write(f"attribution: {report['attribution'].upper()} "
                  f"→ ledger dimension {report['dimension']}\n")
        if report.get("headline"):
            out.write(f"  {report['headline']}\n")
        for dim, d in (report.get("ledger") or {}).items():
            if not isinstance(d, dict):
                continue
            out.write(f"{dim} (total {d.get('total', 0.0):.3f}, "
                      f"{report.get('bills', 0)} bills):\n")
            for row in d.get("tenants") or []:
                out.write(f"  {row['tenant']:<24} {row['value']:>12.4f} "
                          f"{100 * row['share']:>6.1f}%  (±{row['error']:.4f})\n")
            out.write(f"  {'<other>':<24} {d.get('other', 0.0):>12.4f} "
                      f"{100 * d.get('other_share', 0.0):>6.1f}%\n")
        for tenant, counts in (report.get("slo") or {}).items():
            v = counts.get("violated") or {}
            a = counts.get("attained") or {}
            out.write(f"slo {tenant}: ttft {a.get('ttft', 0)}/{a.get('ttft', 0) + v.get('ttft', 0)} "
                      f"attained, tpot {a.get('tpot', 0)}/{a.get('tpot', 0) + v.get('tpot', 0)} attained\n")
        return
    if mode == "incident":
        out.write(f"incident: {report['reason']}  (ts {report.get('ts')})\n")
        out.write(f"attribution: {report['attribution'].upper()}\n")
        for line in report.get("evidence") or []:
            out.write(f"  - {line}\n")
        if report.get("digests"):
            out.write(f"{'window digest':<16} {'count':>7} {'p50 ms':>10} {'p99 ms':>10} {'max ms':>10}\n")
            for name, d in report["digests"].items():
                out.write(f"{name:<16} {d['count']:>7} {d['p50_ms']:>10.2f} "
                          f"{d['p99_ms']:>10.2f} {d['max_ms']:>10.2f}\n")
        rs = report.get("recent_steps") or {}
        out.write(f"recent steps: {rs.get('count', 0)} {rs.get('by_phase', {})}  "
                  f"host-gap p99 {rs.get('host_gap_p99_ms', 0)} ms\n")
        out.write(f"engine at capture: {report.get('running')} running / "
                  f"{report.get('waiting')} waiting, "
                  f"compiles_after_warmup={report.get('compiles_after_warmup')}\n")
        return
    out.write(f"request {report['trace_id']}  ({report['total_ms']:.1f} ms total, "
              f"{report['records']} records)\n")
    out.write(f"attribution: {report['attribution'].upper()}\n")
    for name, ms in (report.get("phases_ms") or {}).items():
        share = (report.get("phase_shares") or {}).get(name, 0.0)
        ctx = (report.get("fleet_context") or {}).get(
            "queue_wait" if name == "queue_wait" else "ttft" if name == "prefill" else "", ""
        )
        out.write(f"  {name:<16} {ms:>10.2f} ms  {100 * share:>5.1f}%  {ctx}\n")
    for m in report.get("modifiers") or []:
        out.write(f"  * {m}\n")
    if report.get("finish_reason"):
        out.write(f"finished: {report['finish_reason']} "
                  f"({report.get('output_tokens')} output tokens)\n")


def main() -> int:
    p = argparse.ArgumentParser(description="dynamo-tpu incident/request autopsy")
    p.add_argument("files", nargs="+",
                   help="incident bundle JSON files and/or JSONL trace files (merged)")
    p.add_argument("--request", default=None, metavar="TRACE_ID",
                   help="attribute one request instead of the incident window")
    p.add_argument("--tenant", action="store_true",
                   help="attribute the incident to tenants (capacity-ledger "
                        "evidence: who consumed the device/KV/queue seconds)")
    p.add_argument("--json", action="store_true", help="emit the report as JSON")
    args = p.parse_args()

    bundles, records = load_inputs(args.files)
    bundle = bundles[0] if bundles else None

    if args.request:
        report = request_report(records, args.request, bundle=bundle)
    elif args.tenant:
        if bundle is None:
            print("--tenant needs an incident bundle", file=sys.stderr)
            return 2
        report = tenant_report(bundle)
    elif bundle is not None:
        report = incident_report(bundle)
    else:
        print("no incident bundle given and no --request trace id", file=sys.stderr)
        return 2

    if args.json:
        json.dump(report, sys.stdout, indent=2, default=str)
        print()
    else:
        render(report)
    return 0 if not report.get("error") else 1


if __name__ == "__main__":
    sys.exit(main())
