#!/usr/bin/env python
"""Render request traces from a JSONL span export.

The serving stack (frontend/worker ``--trace-file``, or ``DYN_TRACE_FILE``)
writes one JSON record per span/event; this tool turns them into a
per-request timeline — the "where did this request's 242 ms go" view — or a
Chrome-trace file for chrome://tracing / Perfetto.

Usage::

    python tools/trace_view.py trace.jsonl                 # list traces
    python tools/trace_view.py trace.jsonl -t <trace_id>   # one timeline
    python tools/trace_view.py trace.jsonl --request <id>  # one request (alias)
    python tools/trace_view.py trace.jsonl --all           # every timeline
    python tools/trace_view.py trace.jsonl --summary       # digest percentiles
    python tools/trace_view.py trace.jsonl --chrome out.json
    python tools/trace_view.py incident_0001_queue_wait_p99.json   # bundle ring

Multiple input files merge (frontend + worker processes each write their
own file; records carry the trace id, so merging is a concat). Incident
bundles written by ``runtime/incidents.py`` are accepted directly: their
embedded trace ring joins the record set, so the black box of a crashed
or anomalous worker renders with the same timelines as a live export.

Crash-time flight recordings are first-class input: a process dying
mid-write leaves a truncated final line (and possibly records missing
fields) — malformed lines are skipped and incomplete records ignored
rather than poisoning the whole file.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List

from dynamo_tpu.runtime.tracing import chrome_trace, read_trace_file
from dynamo_tpu.runtime.telemetry import LatencyDigest

BAR_WIDTH = 40


def read_records(path: str) -> List[dict]:
    """Records from a JSONL trace file OR an incident bundle (whose
    ``trace_ring`` is the per-process black box at capture time)."""
    try:
        from dynamo_tpu.runtime.incidents import BUNDLE_SCHEMA

        with open(path) as f:
            obj = json.load(f)
        if isinstance(obj, dict) and obj.get("schema") == BUNDLE_SCHEMA:
            return [r for r in obj.get("trace_ring") or [] if isinstance(r, dict)]
    except (OSError, ValueError):
        pass
    return read_trace_file(path)


def group_by_trace(records: List[dict]) -> Dict[str, List[dict]]:
    traces: Dict[str, List[dict]] = defaultdict(list)
    for rec in records:
        # Records must carry a timestamp to be placeable on a timeline;
        # a crash mid-serialization can leave ts-less fragments.
        if (
            rec.get("kind") in ("span", "event")
            and rec.get("trace_id")
            and isinstance(rec.get("ts"), (int, float))
        ):
            traces[rec["trace_id"]].append(rec)
    for recs in traces.values():
        recs.sort(key=lambda r: r.get("ts") or 0.0)
    return traces


# --summary: which record fields carry a duration/latency, keyed by the
# phase name the digest reports under. Spans contribute their dur_s under
# the span name; events map their latency attribute explicitly.
_EVENT_LATENCY_ATTRS = {
    "prefill_chunk": ("prefill_chunk", "dur_s"),
    "mixed_ride": ("mixed_ride", "dur_s"),
    "first_token": ("ttft", "ttft_s"),
    "admitted": ("queue_wait", "queue_s"),
}


def summarize(records: List[dict], out=sys.stdout) -> None:
    """Per-phase digest percentiles over every record in the files: span
    durations by span name plus the scheduler's latency-bearing lifecycle
    events (ttft, queue_wait, chunk/ride durations)."""
    digests: Dict[str, LatencyDigest] = {}

    def observe(key: str, value) -> None:
        if not isinstance(value, (int, float)) or value < 0:
            return
        digests.setdefault(key, LatencyDigest()).observe(float(value))

    for rec in records:
        kind = rec.get("kind")
        name = rec.get("name") or "?"
        if kind == "span":
            observe(f"span:{name}", rec.get("dur_s"))
        elif kind == "event":
            mapped = _EVENT_LATENCY_ATTRS.get(name)
            if mapped is not None:
                key, attr = mapped
                observe(key, (rec.get("attrs") or {}).get(attr))
    if not digests:
        out.write("no latency-bearing records found\n")
        return
    out.write(f"{'phase':<20} {'count':>7} {'p50 ms':>10} {'p90 ms':>10} "
              f"{'p99 ms':>10} {'max ms':>10}\n")
    for key in sorted(digests):
        d = digests[key]
        p50, p90, p99 = d.percentiles((0.5, 0.9, 0.99))
        out.write(
            f"{key:<20} {d.count:>7} {1000 * p50:>10.2f} {1000 * p90:>10.2f} "
            f"{1000 * p99:>10.2f} {1000 * d.max:>10.2f}\n"
        )


def trace_summary(trace_id: str, recs: List[dict]) -> str:
    t0 = min(r["ts"] for r in recs)
    t1 = max(r["ts"] + (r.get("dur_s") or 0.0) for r in recs)
    services = sorted({r.get("service") or "?" for r in recs})
    return (
        f"{trace_id}  {len(recs):3d} records  {1000 * (t1 - t0):8.1f} ms  "
        f"[{', '.join(services)}]"
    )


def render_timeline(trace_id: str, recs: List[dict], out=sys.stdout) -> None:
    t0 = min(r["ts"] for r in recs)
    t1 = max(r["ts"] + (r.get("dur_s") or 0.0) for r in recs)
    total = max(t1 - t0, 1e-9)
    out.write(f"trace {trace_id}  ({1000 * total:.1f} ms total)\n")
    for rec in recs:
        off = rec["ts"] - t0
        dur = rec.get("dur_s") or 0.0
        lo = int(BAR_WIDTH * off / total)
        hi = max(lo + 1, int(BAR_WIDTH * (off + dur) / total)) if dur else lo + 1
        bar = " " * lo + ("█" * (hi - lo) if rec["kind"] == "span" else "·")
        bar = bar[:BAR_WIDTH].ljust(BAR_WIDTH)
        label = f"{rec.get('service') or '?':>10}  {rec.get('name') or '?':<16}"
        timing = f"+{1000 * off:8.2f} ms"
        timing += f"  {1000 * dur:8.2f} ms" if dur else " " * 12
        attrs = rec.get("attrs") or {}
        detail = " ".join(f"{k}={v}" for k, v in attrs.items() if k != "request_id")
        out.write(f"  |{bar}| {label} {timing}  {detail}\n")
        for ev in rec.get("events") or []:
            eoff = (ev.get("ts") or rec["ts"]) - t0
            out.write(f"  |{' ' * BAR_WIDTH}|   {'':>8}· {ev.get('name')} +{1000 * eoff:.2f} ms\n")


def main() -> int:
    p = argparse.ArgumentParser(description="dynamo-tpu trace viewer")
    p.add_argument("files", nargs="+",
                   help="JSONL trace files and/or incident bundles (merged)")
    p.add_argument("-t", "--trace-id", default=None, help="render one trace's timeline")
    p.add_argument("--request", default=None, metavar="TRACE_ID",
                   help="filter the timeline/summary to one request's trace id")
    p.add_argument("--all", action="store_true", help="render every trace's timeline")
    p.add_argument("--summary", action="store_true",
                   help="per-phase digest percentiles across all traces")
    p.add_argument("--chrome", default=None, metavar="OUT",
                   help="write a Chrome-trace/Perfetto JSON file")
    args = p.parse_args()
    if args.request:
        args.trace_id = args.request

    records: List[dict] = []
    for path in args.files:
        records.extend(read_records(path))
    if args.request:
        # --request also scopes --summary/--chrome to the one request.
        records = [r for r in records if r.get("trace_id") == args.request]

    if args.summary:
        summarize(records)
        return 0

    traces = group_by_trace(records)
    if not traces:
        print("no trace records found", file=sys.stderr)
        return 1

    if args.chrome:
        selected = records if args.trace_id is None else traces.get(args.trace_id, [])
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(selected), f)
        print(f"wrote {args.chrome} ({len(selected)} records)")
        return 0

    if args.trace_id:
        recs = traces.get(args.trace_id)
        if not recs:
            print(f"trace {args.trace_id} not found", file=sys.stderr)
            return 1
        render_timeline(args.trace_id, recs)
        return 0

    if args.all:
        for tid, recs in sorted(traces.items(), key=lambda kv: kv[1][0]["ts"]):
            render_timeline(tid, recs)
            print()
        return 0

    for tid, recs in sorted(traces.items(), key=lambda kv: kv[1][0]["ts"]):
        print(trace_summary(tid, recs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
