#!/usr/bin/env python
"""Render request traces from a JSONL span export.

The serving stack (frontend/worker ``--trace-file``, or ``DYN_TRACE_FILE``)
writes one JSON record per span/event; this tool turns them into a
per-request timeline — the "where did this request's 242 ms go" view — or a
Chrome-trace file for chrome://tracing / Perfetto.

Usage::

    python tools/trace_view.py trace.jsonl                 # list traces
    python tools/trace_view.py trace.jsonl -t <trace_id>   # one timeline
    python tools/trace_view.py trace.jsonl --all           # every timeline
    python tools/trace_view.py trace.jsonl --chrome out.json

Multiple input files merge (frontend + worker processes each write their
own file; records carry the trace id, so merging is a concat).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List

from dynamo_tpu.runtime.tracing import chrome_trace, read_trace_file

BAR_WIDTH = 40


def group_by_trace(records: List[dict]) -> Dict[str, List[dict]]:
    traces: Dict[str, List[dict]] = defaultdict(list)
    for rec in records:
        if rec.get("kind") in ("span", "event") and rec.get("trace_id"):
            traces[rec["trace_id"]].append(rec)
    for recs in traces.values():
        recs.sort(key=lambda r: r.get("ts") or 0.0)
    return traces


def trace_summary(trace_id: str, recs: List[dict]) -> str:
    t0 = min(r["ts"] for r in recs)
    t1 = max(r["ts"] + (r.get("dur_s") or 0.0) for r in recs)
    services = sorted({r.get("service") or "?" for r in recs})
    return (
        f"{trace_id}  {len(recs):3d} records  {1000 * (t1 - t0):8.1f} ms  "
        f"[{', '.join(services)}]"
    )


def render_timeline(trace_id: str, recs: List[dict], out=sys.stdout) -> None:
    t0 = min(r["ts"] for r in recs)
    t1 = max(r["ts"] + (r.get("dur_s") or 0.0) for r in recs)
    total = max(t1 - t0, 1e-9)
    out.write(f"trace {trace_id}  ({1000 * total:.1f} ms total)\n")
    for rec in recs:
        off = rec["ts"] - t0
        dur = rec.get("dur_s") or 0.0
        lo = int(BAR_WIDTH * off / total)
        hi = max(lo + 1, int(BAR_WIDTH * (off + dur) / total)) if dur else lo + 1
        bar = " " * lo + ("█" * (hi - lo) if rec["kind"] == "span" else "·")
        bar = bar[:BAR_WIDTH].ljust(BAR_WIDTH)
        label = f"{rec.get('service') or '?':>10}  {rec.get('name') or '?':<16}"
        timing = f"+{1000 * off:8.2f} ms"
        timing += f"  {1000 * dur:8.2f} ms" if dur else " " * 12
        attrs = rec.get("attrs") or {}
        detail = " ".join(f"{k}={v}" for k, v in attrs.items() if k != "request_id")
        out.write(f"  |{bar}| {label} {timing}  {detail}\n")
        for ev in rec.get("events") or []:
            eoff = (ev.get("ts") or rec["ts"]) - t0
            out.write(f"  |{' ' * BAR_WIDTH}|   {'':>8}· {ev.get('name')} +{1000 * eoff:.2f} ms\n")


def main() -> int:
    p = argparse.ArgumentParser(description="dynamo-tpu trace viewer")
    p.add_argument("files", nargs="+", help="JSONL trace files (merged)")
    p.add_argument("-t", "--trace-id", default=None, help="render one trace's timeline")
    p.add_argument("--all", action="store_true", help="render every trace's timeline")
    p.add_argument("--chrome", default=None, metavar="OUT",
                   help="write a Chrome-trace/Perfetto JSON file")
    args = p.parse_args()

    records: List[dict] = []
    for path in args.files:
        records.extend(read_trace_file(path))
    traces = group_by_trace(records)
    if not traces:
        print("no trace records found", file=sys.stderr)
        return 1

    if args.chrome:
        selected = records if args.trace_id is None else traces.get(args.trace_id, [])
        with open(args.chrome, "w") as f:
            json.dump(chrome_trace(selected), f)
        print(f"wrote {args.chrome} ({len(selected)} records)")
        return 0

    if args.trace_id:
        recs = traces.get(args.trace_id)
        if not recs:
            print(f"trace {args.trace_id} not found", file=sys.stderr)
            return 1
        render_timeline(args.trace_id, recs)
        return 0

    if args.all:
        for tid, recs in sorted(traces.items(), key=lambda kv: kv[1][0]["ts"]):
            render_timeline(tid, recs)
            print()
        return 0

    for tid, recs in sorted(traces.items(), key=lambda kv: kv[1][0]["ts"]):
        print(trace_summary(tid, recs))
    return 0


if __name__ == "__main__":
    sys.exit(main())
