"""Interleaved A/B decode profiling — robust to drifting chip performance.

Runs each variant in round-robin rounds and reports per-round times + the
median, so variant deltas are comparable even when the (shared/tunneled)
chip's absolute speed drifts between rounds.
"""

from __future__ import annotations

import os
import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama


def main():
    model = os.environ.get("BENCH_MODEL", "llama-3.2-1b")
    B = int(os.environ.get("BENCH_BATCH", "8"))
    ctx = int(os.environ.get("BENCH_CTX", "1024"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "5"))
    iters = int(os.environ.get("BENCH_ITERS", "30"))
    cfg = get_config(model).replace(max_seq_len=2048)
    num_blocks = B * (ctx // cfg.block_size + 4) + 8

    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)

    needed = (ctx + 64) // cfg.block_size
    width = min((needed + 15) // 16 * 16, cfg.max_seq_len // cfg.block_size)
    tables_np = np.zeros((B, width), dtype=np.int32)
    for i in range(B):
        tables_np[i, :needed] = (np.arange(needed) + 1 + i * needed) % (num_blocks - 1) + 1
    tables = jnp.asarray(tables_np)
    active = jnp.ones((B,), dtype=bool)
    toks = jnp.zeros((B,), dtype=jnp.int32)
    pos = jnp.full((B,), ctx, dtype=jnp.int32)

    variants = {}

    def add_decode_variant(name, impl):
        c = cfg.replace(attention_impl=impl)
        step = jax.jit(
            lambda p, k, v: llama.decode(p, c, k, v, toks, pos, tables, active),
            donate_argnums=(1, 2),
        )
        cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
        state = {"k": cache.k, "v": cache.v}

        def run_once():
            logits, state["k"], state["v"] = step(params, state["k"], state["v"])
            return logits

        variants[name] = run_once

    add_decode_variant("gather", "gather")

    # Weights-only floor (no cache, no attention reads).
    def make_floor():
        def floor_fn(p, t):
            h = p["embed"].at[t].get(mode="clip")

            def layer_fn(h, lp):
                x = llama.rms_norm(h, lp["attn_norm"], cfg.rms_norm_eps)
                q = x @ lp["wq"]
                kk = x @ lp["wk"]
                vv = x @ lp["wv"]
                a = q + jnp.concatenate([kk, vv, kk, vv], axis=-1) * 0
                h = h + a @ lp["wo"]
                x = llama.rms_norm(h, lp["mlp_norm"], cfg.rms_norm_eps)
                h = h + (jax.nn.silu(x @ lp["w_gate"]) * (x @ lp["w_up"])) @ lp["w_down"]
                return h, None

            h, _ = jax.lax.scan(layer_fn, h, p["layers"])
            h = llama.rms_norm(h, p["final_norm"], cfg.rms_norm_eps)
            return (h @ p["embed"].T).astype(jnp.float32)

        f = jax.jit(floor_fn)

        def run_once():
            return f(params, toks)

        return run_once

    variants["floor"] = make_floor()

    # Warmup all.
    for name, fn in variants.items():
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        print(f"warmup {name}: {time.perf_counter()-t0:.1f}s", flush=True)

    results = {name: [] for name in variants}
    for r in range(rounds):
        for name, fn in variants.items():
            out = fn()
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            jax.block_until_ready(out)
            ms = (time.perf_counter() - t0) / iters * 1000
            results[name].append(ms)
            print(f"round {r} {name:8s}: {ms:7.3f} ms", flush=True)

    for name, times in results.items():
        med = statistics.median(times)
        print(f"{name:8s}: med {med:7.3f} ms   rounds: " + " ".join(f"{t:6.2f}" for t in times))


if __name__ == "__main__":
    main()
