"""Ablate decode_multi's cost components on the real chip.

Re-assembles the production window-decode step from llama.py's internals
with switchable pieces, so each component's cost is measured inside the
same dispatch/amortization structure as production (per-call tunnel
overhead makes out-of-context microbenchmarks useless on axon backends —
measured: a single gather+attend dispatch reads as ~1 ms when the full
16-layer step is 10 ms).

Pieces: embed+qkv/o+mlp (weights), prefix gather+attend, window attend,
lm_head, sampling, window scatter.

Usage: python tools/ablate_decode.py [batch] [ctx] [width]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama
from dynamo_tpu.engine.models.llama import (
    _attend_piece,
    _gather_kv,
    _merge_pieces,
    _mlp,
    _scatter_kv,
    apply_rope,
    decode_targets,
    rms_norm,
)
from dynamo_tpu.engine.sampling import sample_batch

batch = int(sys.argv[1]) if len(sys.argv) > 1 else 32
ctx_len = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
width = int(sys.argv[3]) if len(sys.argv) > 3 else 80
window, steps = 16, 256

cfg = get_config("llama-3.2-1b").replace(max_seq_len=4096)
params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8


def ablated_decode_multi(
    params, c, k_cache, v_cache, tokens, positions, block_tables, active,
    temps, top_ks, top_ps, rng_key, num_steps,
    *, do_gather=True, do_window=True, do_lm_head=True, do_sample=True,
    do_mlp=True, do_scatter=True,
):
    B = tokens.shape[0]
    L, KVH, HD = c.num_layers, c.num_kv_heads, c.head_dim
    bs = c.block_size
    _, _, mask0 = decode_targets(positions, block_tables, active, bs)
    kvh, G, hd = KVH, c.num_heads // KVH, c.head_dim
    scale = hd**-0.5
    N = k_cache.shape[1]
    k_flat = k_cache.reshape(L * N, bs, kvh, hd)
    v_flat = v_cache.reshape(L * N, bs, kvh, hd)
    ctx = block_tables.shape[1] * bs
    w = num_steps

    def layer_body(h, xs, poss, k_win_l, v_win_l, small_mask):
        lp, l = xs
        x = rms_norm(h, lp["attn_norm"], c.rms_norm_eps)
        q = (x @ lp["wq"]).reshape(B, 1, c.num_heads, hd)
        k = (x @ lp["wk"]).reshape(B, 1, kvh, hd)
        v = (x @ lp["wv"]).reshape(B, 1, kvh, hd)
        q = apply_rope(q, poss[:, None], c.rope_theta)[:, 0]
        k = apply_rope(k, poss[:, None], c.rope_theta)[:, 0]
        v = v[:, 0]
        qg = q.reshape(B, kvh, G, hd)
        pieces = []
        if do_gather:
            tables_l = block_tables + l * N
            k_ctx = _gather_kv(k_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
            v_ctx = _gather_kv(v_flat, tables_l, h.dtype).reshape(B, ctx, kvh, hd)
            pieces.append(_attend_piece(qg, k_ctx, v_ctx, mask0, scale))
        if do_window:
            k_small = jnp.concatenate([jnp.swapaxes(k_win_l, 0, 1), k[:, None]], axis=1)
            v_small = jnp.concatenate([jnp.swapaxes(v_win_l, 0, 1), v[:, None]], axis=1)
            pieces.append(_attend_piece(qg, k_small, v_small, small_mask, scale))
        if len(pieces) == 2:
            attn = _merge_pieces(*pieces[0], *pieces[1]).astype(h.dtype)
        elif pieces:
            m, lw, acc = pieces[0]
            attn = (acc / jnp.maximum(lw, 1e-30)[..., None]).astype(h.dtype)
        else:
            attn = qg
        h = h + attn.reshape(B, c.q_size) @ lp["wo"]
        if do_mlp:
            x = rms_norm(h, lp["mlp_norm"], c.rms_norm_eps)
            h = h + _mlp(x, lp, c, valid=active)
        return h, (k, v)

    def body(i, state):
        toks, k_win, v_win, out, key = state
        poss = positions + i
        h = params["embed"].at[toks].get(mode="clip")
        small_mask = jnp.concatenate(
            [jnp.broadcast_to((jnp.arange(w, dtype=jnp.int32) < i)[None, :], (B, w)),
             jnp.ones((B, 1), dtype=bool)], axis=1)
        h, (k_rows, v_rows) = lax.scan(
            lambda hh, xs: layer_body(
                hh, xs, poss,
                k_win[xs[1]], v_win[xs[1]], small_mask),
            h, (params["layers"], jnp.arange(L, dtype=jnp.int32)),
        )
        k_win = k_win.at[:, i].set(k_rows)
        v_win = v_win.at[:, i].set(v_rows)
        h = rms_norm(h, params["final_norm"], c.rms_norm_eps)
        if do_lm_head:
            head = params.get("lm_head")
            logits = (h @ (head if head is not None else params["embed"].T)).astype(jnp.float32)
        else:
            logits = jnp.zeros((B, 256), jnp.float32).at[:, :128].set(h[:, :128].astype(jnp.float32))
        key, sub = jax.random.split(key)
        if do_sample:
            nxt = sample_batch(logits, temps, top_ks, top_ps, sub).astype(jnp.int32)
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out = out.at[i].set(nxt)
        return (nxt, k_win, v_win, out, key)

    wdtype = params["embed"].dtype
    k_win0 = jnp.zeros((L, num_steps, B, KVH, HD), dtype=wdtype)
    v_win0 = jnp.zeros((L, num_steps, B, KVH, HD), dtype=wdtype)
    out0 = jnp.zeros((num_steps, B), dtype=jnp.int32)
    _, k_win, v_win, out, _ = lax.fori_loop(
        0, num_steps, body, (tokens, k_win0, v_win0, out0, rng_key))
    if do_scatter:
        steps_i = jnp.arange(num_steps, dtype=jnp.int32)
        slots = jnp.where(active[None, :], positions[None, :] + steps_i[:, None], 0)
        tgt_blocks = jnp.where(
            active[None, :], block_tables[jnp.arange(B)[None, :], slots // bs], 0)
        tgt_offs = slots % bs
        layer_idx = jnp.broadcast_to(
            jnp.arange(L, dtype=jnp.int32)[:, None, None], (L, num_steps, B))
        k_cache = _scatter_kv(k_cache, layer_idx, tgt_blocks[None], tgt_offs[None], k_win)
        v_cache = _scatter_kv(v_cache, layer_idx, tgt_blocks[None], tgt_offs[None], v_win)
    return out, k_cache, v_cache


def measure(label, **flags):
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    tables = jnp.tile(jnp.arange(1, width + 1, dtype=jnp.int32)[None, :], (batch, 1))
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), bool)
    zf = jnp.zeros((batch,), jnp.float32)
    zi = jnp.zeros((batch,), jnp.int32)
    of = jnp.ones((batch,), jnp.float32)
    fn = jax.jit(
        lambda p, k, v, t, pos, key: ablated_decode_multi(
            p, cfg, k, v, t, pos, tables, active, zf, zi, of, key, window, **flags),
        donate_argnums=(1, 2))
    toks = jnp.zeros((batch,), jnp.int32)
    pos = jnp.full((batch,), ctx_len, jnp.int32)
    k, v = cache.k, cache.v
    out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(0)); np.asarray(out)
    nw = max(1, steps // window)
    t0 = time.perf_counter()
    for i in range(nw):
        out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(i))
    np.asarray(out)
    dt = (time.perf_counter() - t0) / (nw * window)
    print(f"{label:34s}: {dt*1e3:7.3f} ms/step", flush=True)
    return dt


full = measure("full (all pieces)")
measure("no sampling (argmax)", do_sample=False)
measure("no lm_head/sampling", do_lm_head=False, do_sample=False)
measure("no prefix gather", do_gather=False)
measure("no window piece", do_window=False)
measure("no mlp", do_mlp=False)
measure("no final scatter", do_scatter=False)
measure("weights only (no attn pieces)", do_gather=False, do_window=False)
