"""Router-benefit benchmark: KV-aware routing vs round-robin over a mocker
fleet, swept by shared-prefix ratio.

This is the measurement behind the reference's headline routing claim
(ref: benchmarks/router/prefix_ratio_benchmark.py — ~3x TTFT from KV
routing at high prefix share): timed mocker workers with REAL prefix
caches serve interleaved requests from G prefix GROUPS — each group
shares the leading fraction ``p`` of its tokens — under cache pressure
(the aggregate group prefixes exceed one worker's blocks). KV routing
partitions groups across workers so each prefix stays warm on its home
worker; round-robin cycles every group through every worker, evicting
and re-prefilling constantly. The win grows with ``p``.

The wire-path sweep here measures the whole distributed stack (router
index, KV events, pub/sub + TCP, mocker timing model) — its single-core
asyncio queueing noise floors the measurable ratio. The ENGINE-side
speedup the routing hint buys — real Schedulers skipping real prefill
FLOPs — is measured by ``bench.py``'s ``prefix_reuse`` section: 4.4×
mean TTFT at 0.9 prefix ratio, with engine-reported ``cached_tokens``
asserted equal to the blocks actually served from cache and 0 XLA
compiles after warmup.

Prints ONE JSON line:
  {"isl": ..., "workers": N, "sweep": [{"prefix_ratio": p,
    "ttft_kv_ms": ..., "ttft_rr_ms": ..., "speedup": ...,
    "cached_tokens_kv": ..., "cached_tokens_rr": ...}, ...]}

TTFTs are in emulated-model milliseconds scaled by the mocker speedup —
absolute values track the timing model; the kv/rr RATIO is the result.

Usage: python tools/bench_router_prefix.py [--quick]
"""

import asyncio
import json
import random
import sys
import time

from dynamo_tpu.llm.kv_router import (
    KvEventPublisher,
    KvPushRouter,
    KvRouterConfig,
    WorkerMetricsPublisher,
)
from dynamo_tpu.llm.mocker import MockEngineArgs, MockTpuEngine
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.push_router import PushRouter, RouterMode

WORKERS = 4
GROUPS = 8
ISL = 1024  # prefill compute must dominate the wire/tick overhead (~3 ms)
OSL = 4
# Real time == emulated time: speedup 2 halved every simulated duration
# while the REAL wire/tick overhead (~3 ms) stayed put, so the reported
# (emulated-scaled) TTFTs carried a doubled overhead floor that diluted
# the hit-side advantage — the routing win is measured, don't compress it.
SPEEDUP = 1.0
NUM_BLOCKS = 256  # per worker: ~3 group prefixes fit, all 8 never do
CHUNK = 512  # mocker prefill chunk — the engine's mixed_prefill_budget, so a
# cold miss stalls batchmates one chunk at a time, not a whole prompt


async def spawn_fleet(drt, ns):
    ep = drt.namespace(ns).component("mocker").endpoint("generate")
    fleet = []
    for _ in range(WORKERS):
        engine = MockTpuEngine(
            MockEngineArgs(
                speedup_ratio=SPEEDUP, num_blocks=NUM_BLOCKS, max_batch=8,
                max_prefill_chunk=CHUNK,
            )
        )
        handle = await ep.serve_endpoint(engine.generate, stats_handler=engine.stats_handler)
        wid = handle.instance.instance_id
        pub = KvEventPublisher(drt, ep.namespace, ep.component, wid)
        pub.start()
        engine.set_kv_event_sink(lambda ev, p=pub: p.publish(ev))
        mpub = WorkerMetricsPublisher(
            drt, ep.namespace, ep.component, wid, engine.metrics, interval_s=0.05
        )
        mpub.start()
        drt.local_engines.pop(wid)  # force the wire path
        fleet.append((engine, handle, pub, mpub))
    client = await ep.client()
    await client.wait_for_instances(WORKERS, timeout=10)
    return ep, client, fleet


def make_requests(n, prefix_ratio, seed):
    """(warmup, measured): one request per group in group order (EVERY
    group's prefix gets established somewhere before measurement — a
    shuffled warmup sample left some groups cold, so the measured phase
    timed cold establishment instead of routing quality), then n measured
    requests interleaved across the GROUPS prefix groups (shuffled —
    aligned striding would hand round-robin a perfect group partition by
    accident since GROUPS % WORKERS == 0; real traffic is unordered)."""
    rng = random.Random(seed)
    shared = [
        [rng.randrange(1, 30000) for _ in range(int(ISL * prefix_ratio))]
        for _ in range(GROUPS)
    ]

    def req(g):
        suffix = [rng.randrange(1, 30000) for _ in range(ISL - len(shared[g]))]
        return shared[g] + suffix

    warmup = [req(g) for g in range(GROUPS)]
    order = [i % GROUPS for i in range(n)]
    rng.shuffle(order)
    return warmup, [req(g) for g in order]


async def run_policy(policy, warmup, prompts, drt, ns):
    """Serve all prompts through the given policy; return (mean ttft ms,
    total mocker-cached tokens)."""
    ep, client, fleet = await spawn_fleet(drt, ns)
    router = None
    rr = None
    if policy == "kv":
        router = await KvPushRouter.create(client, KvRouterConfig(block_size=16))
    else:
        rr = PushRouter(client, RouterMode.ROUND_ROBIN)

    async def one(tokens):
        req = {
            "token_ids": tokens,
            "sampling_options": {"temperature": 0.0},
            "stop_conditions": {"max_tokens": OSL},
        }
        t0 = time.perf_counter()
        ttft = None
        if router is not None:
            stream = router.generate(req, Context())
        else:
            stream = rr.generate(req)
        async for item in stream:
            data = getattr(item, "data", item)
            if data and ttft is None:
                ttft = time.perf_counter() - t0
        return ttft if ttft is not None else time.perf_counter() - t0

    # Warm every group's prefix sequentially (both policies get the same
    # warmup), then measure with bounded concurrency (the realistic
    # arrival pattern).
    ttfts = []
    for tokens in warmup:
        await one(tokens)
    await asyncio.sleep(0.3)  # KV events reach the indexer
    sem = asyncio.Semaphore(4)

    async def guarded(tokens):
        async with sem:
            ttfts.append(await one(tokens))

    await asyncio.gather(*[guarded(t) for t in prompts])
    cached = sum(e.cached_tokens_total for e, *_ in fleet)
    if router is not None:
        await router.close()
    for e, handle, pub, mpub in fleet:
        await handle.stop()
        await pub.stop()
        await mpub.stop()
    mean_ms = 1000.0 * sum(ttfts) / max(len(ttfts), 1)
    return mean_ms * SPEEDUP, cached  # report emulated-model time


async def main():
    quick = "--quick" in sys.argv
    ratios = [0.0, 0.5, 0.9] if quick else [0.0, 0.25, 0.5, 0.75, 0.9]
    n = 32 if quick else 56
    drt = await DistributedRuntime.detached()
    sweep = []
    for i, p in enumerate(ratios):
        warmup, prompts = make_requests(n, p, seed=1234 + i)
        kv_ms, kv_cached = await run_policy("kv", warmup, prompts, drt, f"rpx_kv_{i}")
        rr_ms, rr_cached = await run_policy("rr", warmup, prompts, drt, f"rpx_rr_{i}")
        sweep.append(
            {
                "prefix_ratio": p,
                "ttft_kv_ms": round(kv_ms, 2),
                "ttft_rr_ms": round(rr_ms, 2),
                "speedup": round(rr_ms / max(kv_ms, 1e-9), 2),
                "cached_tokens_kv": kv_cached,
                "cached_tokens_rr": rr_cached,
            }
        )
    await drt.shutdown()
    print(json.dumps({
        "isl": ISL, "workers": WORKERS, "groups": GROUPS, "osl": OSL,
        "worker_blocks": NUM_BLOCKS, "sweep": sweep,
    }))


if __name__ == "__main__":
    asyncio.run(main())
