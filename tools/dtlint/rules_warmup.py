"""WARM001 — static warmup coverage of the jit dispatch key space.

The flight recorder proves ``compiles_after_warmup_total == 0`` *dynamically*
— but only for the key space a bench run happens to exercise. This rule is
the static twin: every ``record_exec("<kind>", <key>)`` dispatch site on the
serving paths of the warmup-scope files must have a matching registration
inside ``Scheduler.warmup()`` (or a helper it calls), with a compatible key
arity. A serving kind warmup never touches is a guaranteed mid-traffic
compile the moment that path first fires — exactly the regression class the
0-compile invariant exists to prevent.

Key arities are derived from the key expression: tuple literals count their
elements, ``+``-concatenation sums, conditional suffixes like
``+ ((flag,) if cond else ())`` produce arity *sets* ({4, 5}), and names
resolve through local tuple assignments. A serving site and its warmup twin
agree when their arity sets intersect (the recorder keys executables by
``(kind,) + tuple(key)``, so kind+arity is the static shape of the key
space; the element *values* are runtime rungs the bench still covers).

``static_warmup_report()`` exports the same enumeration for bench.py, which
cross-checks it against the recorder's dynamically observed executable keys
— the static and dynamic views of the 0-compile invariant must agree.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.dtlint.callgraph import project_graph, split_gid
from tools.dtlint.core import (
    Finding, LintConfig, ProjectIndex, dotted, enclosing_map, qualname_at,
    rule,
)


def _tuple_arities(expr: ast.AST, local_tuples: Dict[str, Set[int]]) -> Optional[Set[int]]:
    """Possible element counts of a tuple-valued key expression, or None
    when the shape is not statically evident."""
    if isinstance(expr, ast.Tuple):
        if any(isinstance(e, ast.Starred) for e in expr.elts):
            return None
        return {len(expr.elts)}
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        l = _tuple_arities(expr.left, local_tuples)
        r = _tuple_arities(expr.right, local_tuples)
        if l is None or r is None:
            return None
        return {a + b for a in l for b in r}
    if isinstance(expr, ast.IfExp):
        l = _tuple_arities(expr.body, local_tuples)
        r = _tuple_arities(expr.orelse, local_tuples)
        if l is None or r is None:
            return None
        return l | r
    if isinstance(expr, ast.Name):
        return local_tuples.get(expr.id)
    if isinstance(expr, ast.Call) and dotted(expr.func) == "tuple" and expr.args:
        return _tuple_arities(expr.args[0], local_tuples)
    return None


def _local_tuple_arities(fn: ast.AST) -> Dict[str, Set[int]]:
    """{var: arity set} for locals assigned tuple literals (handles the
    ``mixed_key = (a, b, c, d)`` then ``mixed_key + (...)`` pattern)."""
    out: Dict[str, Set[int]] = {}
    for _ in range(2):  # second pass resolves tuple-from-tuple chains
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                ar = _tuple_arities(node.value, out)
                if ar is not None:
                    out[node.targets[0].id] = ar
    return out


class DispatchSite:
    __slots__ = ("kind", "file", "line", "qualname", "arities")

    def __init__(self, kind: str, file: str, line: int, qualname: str,
                 arities: Optional[Set[int]]) -> None:
        self.kind = kind
        self.file = file
        self.line = line
        self.qualname = qualname
        self.arities = arities


def _collect_sites(index: ProjectIndex) -> List[DispatchSite]:
    cfg = index.config
    sites: List[DispatchSite] = []
    for mod in index.modules:
        if mod.relpath not in cfg.warmup_scopes and not any(
            mod.relpath.endswith("/" + s) for s in cfg.warmup_scopes
        ):
            continue
        line_map = enclosing_map(mod.tree)
        fn_arities: Dict[str, Dict[str, Set[int]]] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if not name or name.split(".")[-1] != "record_exec":
                continue
            if len(node.args) < 1:
                continue
            karg = node.args[0]
            if not (isinstance(karg, ast.Constant) and isinstance(karg.value, str)):
                continue
            q = qualname_at(line_map, node.lineno)
            if q not in fn_arities:
                fn = None
                for fq, f in _functions_cache(mod):
                    if fq == q:
                        fn = f
                        break
                fn_arities[q] = _local_tuple_arities(fn) if fn is not None else {}
            arities = (_tuple_arities(node.args[1], fn_arities[q])
                       if len(node.args) > 1 else None)
            sites.append(DispatchSite(karg.value, mod.relpath, node.lineno, q, arities))
    return sites


_FN_CACHE: Dict[int, List[Tuple[str, ast.AST]]] = {}


def _functions_cache(mod) -> List[Tuple[str, ast.AST]]:
    from tools.dtlint.core import iter_functions

    key = id(mod)
    if key not in _FN_CACHE:
        if len(_FN_CACHE) > 64:
            _FN_CACHE.clear()
        _FN_CACHE[key] = list(iter_functions(mod.tree))
    return _FN_CACHE[key]


def _warmup_closure(index: ProjectIndex) -> Set[Tuple[str, str]]:
    """(relpath, qualname) pairs reachable from the warmup entry point —
    registrations inside helpers warmup calls count as warmed."""
    cfg = index.config
    pg = project_graph(index)
    roots = []
    for g, info in pg.funcs.items():
        relpath, q = split_gid(g)
        if q == cfg.warmup_func and any(
            relpath == s or relpath.endswith("/" + s) for s in cfg.warmup_scopes
        ):
            roots.append(g)
    return {split_gid(g) for g in pg.reachable(roots)}


def enumerate_warmup(index: ProjectIndex):
    """(warmed {kind: arity set}, serving [DispatchSite]) over the
    warmup-scope files."""
    sites = _collect_sites(index)
    closure = _warmup_closure(index)
    warmed: Dict[str, Set[int]] = {}
    serving: List[DispatchSite] = []
    for s in sites:
        if (s.file, s.qualname) in closure:
            cur = warmed.setdefault(s.kind, set())
            if s.arities:
                cur |= s.arities
        else:
            serving.append(s)
    return warmed, serving


@rule("WARM001", "serving-path jit dispatch keys (record_exec kinds/arities) not pre-registered by Scheduler.warmup()")
def warm001(index: ProjectIndex) -> List[Finding]:
    warmed, serving = enumerate_warmup(index)
    if not warmed and not serving:
        return []
    findings: List[Finding] = []
    for s in serving:
        mod = index.module(s.file)
        if mod is not None and mod.suppressed("WARM001", s.line):
            continue
        if s.kind not in warmed:
            findings.append(Finding(
                "WARM001", s.file, s.line, s.qualname,
                f"dispatch kind '{s.kind}' is never registered by warmup() — "
                f"the first request on this path compiles mid-traffic "
                f"(breaks the 0-post-warmup-compiles invariant)",
                key=f"unwarmed:{s.kind}",
            ))
            continue
        warm_ar = warmed[s.kind]
        if s.arities and warm_ar and not (s.arities & warm_ar):
            findings.append(Finding(
                "WARM001", s.file, s.line, s.qualname,
                f"dispatch kind '{s.kind}' keys {sorted(s.arities)}-tuples "
                f"here but warmup() registers {sorted(warm_ar)}-tuples — "
                f"the serving key shape can never hit the warmed executable",
                key=f"arity:{s.kind}",
            ))
    return findings


def static_warmup_report(root: str) -> dict:
    """Bench-facing export: the statically enumerated warmup key space.

    ``{"warmed": {kind: [arities]}, "serving": {kind: [arities]}}`` —
    bench.py asserts the flight recorder's dynamically compiled executable
    kinds/arities are a subset of the static ``warmed`` set, closing the
    loop between this rule and the runtime 0-compile gate. Pure ast, no
    JAX import.
    """
    index = ProjectIndex(LintConfig(root=root))
    warmed, serving = enumerate_warmup(index)
    serving_k: Dict[str, Set[int]] = {}
    for s in serving:
        cur = serving_k.setdefault(s.kind, set())
        if s.arities:
            cur |= s.arities
    return {
        "warmed": {k: sorted(v) for k, v in sorted(warmed.items())},
        "serving": {k: sorted(v) for k, v in sorted(serving_k.items())},
    }
