"""Call graphs + jit/pallas root discovery.

Two tiers:

- ``ModuleGraph`` (v1): deliberately module-local and name-based —
  ``f(...)`` resolves to a function defined in the same module,
  ``self.m(...)`` to a method of the enclosing class.
- ``ProjectGraph`` (v2): whole-program. Resolves imports (absolute and
  relative, aliased), ``self.``/``cls.`` method dispatch including
  single-level inheritance, class-attribute callables
  (``self._f_jit = jax.jit(f)`` then ``self._f_jit(...)``), and
  constructor-/annotation-typed attributes
  (``self.flight = FlightRecorder()`` then ``self.flight.record_exec``),
  plus a project-wide fixpoint pass classifying every function's return
  value as host/device/unknown. This is what lets JIT001/SYNC001/DON001
  follow the frontend→router→worker→scheduler paths that the module-local
  graph silently missed, without pretending to be a full type checker:
  anything it cannot resolve stays unresolved (no guessing).

Functions are identified project-wide by ``"<relpath>::<qualname>"``
strings (a *gid*).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.dtlint.core import ProjectIndex, SourceModule, dotted, iter_functions

_JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PALLAS_CALLS = {"pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call"}
_PARTIAL = {"partial", "functools.partial"}


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST
    cls: Optional[str]            # enclosing class name, if a method
    calls: Set[str] = field(default_factory=set)   # resolved callee qualnames


@dataclass
class JitWrapper:
    """One ``jax.jit(fn, ...)`` / ``@jax.jit`` / ``pallas_call(kernel)``
    site: the wrapped function (when resolvable), the name the wrapper is
    bound to (module global or ``self.X`` attribute), and donation info."""

    target: Optional[str]          # wrapped function qualname, if resolved
    bound_name: Optional[str]      # "name" or "self.attr" the wrapper binds to
    line: int
    # Unresolved target reference as a dotted string ("llama.prefill") —
    # ProjectGraph re-resolves these across module boundaries.
    target_dotted: Optional[str] = None
    # When the wrapped object is a lambda (the scheduler's dispatch style:
    # ``jax.jit(lambda p, k, v: model.decode(...))``), the lambda node and
    # the enclosing scope — ProjectGraph resolves the calls in its body.
    target_lambda: Optional[ast.Lambda] = None
    scope: Optional[str] = None
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    kind: str = "jit"              # "jit" | "pallas"


class ModuleGraph:
    """Call graph + jit roots for ONE module."""

    def __init__(self, mod: SourceModule) -> None:
        self.mod = mod
        self.funcs: Dict[str, FuncInfo] = {}
        self.wrappers: List[JitWrapper] = []
        self._collect_funcs()
        self._collect_wrappers()
        self._collect_calls()

    # -- collection ----------------------------------------------------------
    def _collect_funcs(self) -> None:
        for q, fn in iter_functions(self.mod.tree):
            cls = q.rsplit(".", 2)[-2] if "." in q else None
            self.funcs[q] = FuncInfo(qualname=q, node=fn, cls=cls)

    def _resolve_func_ref(self, node: ast.AST, scope: Optional[str]) -> Optional[str]:
        """Resolve a function reference (Name / self.attr) to a qualname
        defined in this module. ``scope`` is the enclosing qualname prefix
        used to find nested defs and sibling methods."""
        name = dotted(node)
        if not name:
            return None
        if name.startswith("self."):
            attr = name[len("self."):]
            if scope and "." in scope:
                cls = scope.rsplit(".", 1)[0]
                cand = f"{cls}.{attr}"
                if cand in self.funcs:
                    return cand
            return None
        # nested def in the same scope wins, then module-level
        if scope:
            cand = f"{scope}.{name}"
            if cand in self.funcs:
                return cand
        if name in self.funcs:
            return name
        return None

    @staticmethod
    def _int_tuple(node: Optional[ast.AST]) -> Tuple[int, ...]:
        if node is None:
            return ()
        try:
            v = ast.literal_eval(node)
        except (ValueError, SyntaxError):
            return ()
        if isinstance(v, int):
            return (v,)
        if isinstance(v, (tuple, list)):
            return tuple(x for x in v if isinstance(x, int))
        return ()

    @staticmethod
    def _str_tuple(node: Optional[ast.AST]) -> Tuple[str, ...]:
        if node is None:
            return ()
        try:
            v = ast.literal_eval(node)
        except (ValueError, SyntaxError):
            return ()
        if isinstance(v, str):
            return (v,)
        if isinstance(v, (tuple, list)):
            return tuple(x for x in v if isinstance(x, str))
        return ()

    def _wrapper_from_call(
        self, call: ast.Call, scope: Optional[str], bound: Optional[str]
    ) -> Optional[JitWrapper]:
        callee = dotted(call.func)
        kind = None
        if callee in _JIT_CALLS:
            kind = "jit"
        elif callee in _PALLAS_CALLS:
            kind = "pallas"
        elif callee in _PARTIAL and call.args:
            inner = dotted(call.args[0])
            if inner in _JIT_CALLS:
                # partial(jax.jit, static_argnums=...) used as a decorator
                kw = {k.arg: k.value for k in call.keywords if k.arg}
                return JitWrapper(
                    target=None, bound_name=bound, line=call.lineno,
                    static_argnums=self._int_tuple(kw.get("static_argnums")),
                    static_argnames=self._str_tuple(kw.get("static_argnames")),
                    donate_argnums=self._int_tuple(kw.get("donate_argnums")),
                    donate_argnames=self._str_tuple(kw.get("donate_argnames")),
                )
            return None
        if kind is None:
            return None
        target = self._resolve_func_ref(call.args[0], scope) if call.args else None
        target_dotted = dotted(call.args[0]) if call.args else None
        target_lambda = None
        if call.args and isinstance(call.args[0], ast.Lambda):
            target_lambda = call.args[0]
        elif call.args and isinstance(call.args[0], ast.Call):
            # jax.jit(partial(f, ...)): the partial's first arg is the target.
            inner = call.args[0]
            if dotted(inner.func) in _PARTIAL and inner.args:
                target = self._resolve_func_ref(inner.args[0], scope)
                target_dotted = dotted(inner.args[0])
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        return JitWrapper(
            target=target, bound_name=bound, line=call.lineno, kind=kind,
            target_dotted=target_dotted, target_lambda=target_lambda, scope=scope,
            static_argnums=self._int_tuple(kw.get("static_argnums")),
            static_argnames=self._str_tuple(kw.get("static_argnames")),
            donate_argnums=self._int_tuple(kw.get("donate_argnums")),
            donate_argnames=self._str_tuple(kw.get("donate_argnames")),
        )

    def _collect_wrappers(self) -> None:
        # Decorated defs: @jax.jit, @partial(jax.jit, ...), @pl.pallas_call(...)
        for q, info in self.funcs.items():
            for dec in getattr(info.node, "decorator_list", []):
                w = None
                name = dotted(dec)
                if name in _JIT_CALLS:
                    w = JitWrapper(target=q, bound_name=q, line=dec.lineno)
                elif isinstance(dec, ast.Call):
                    w = self._wrapper_from_call(dec, None, q)
                    if w is not None:
                        w.target = q
                if w is not None:
                    self.wrappers.append(w)

        # Call-expression wrappers anywhere: x = jax.jit(f, ...) /
        # self._f_jit = jax.jit(f) / res = pl.pallas_call(kernel, ...)(args)
        line_scope = {}
        for q, info in self.funcs.items():
            end = getattr(info.node, "end_lineno", info.node.lineno)
            for ln in range(info.node.lineno, end + 1):
                line_scope[ln] = q

        class V(ast.NodeVisitor):
            def __init__(v):
                v.out: List[JitWrapper] = []

            def visit_Assign(v, node: ast.Assign):
                if isinstance(node.value, ast.Call):
                    scope = line_scope.get(node.lineno)
                    bound = dotted(node.targets[0]) if len(node.targets) == 1 else None
                    w = self._wrapper_from_call(node.value, scope, bound)
                    if w is not None:
                        v.out.append(w)
                        return
                v.generic_visit(node)

            def visit_Call(v, node: ast.Call):
                scope = line_scope.get(node.lineno)
                w = self._wrapper_from_call(node, scope, None)
                if w is not None:
                    v.out.append(w)
                v.generic_visit(node)

        vis = V()
        vis.visit(self.mod.tree)
        # De-dup (an Assign's Call is visited twice).
        seen = set()
        for w in vis.out + self.wrappers:
            k = (w.line, w.bound_name, w.target)
            if k not in seen:
                seen.add(k)
        dedup: List[JitWrapper] = []
        seen = set()
        for w in self.wrappers + vis.out:
            k = (w.line, w.bound_name, w.target, w.kind)
            if k not in seen:
                seen.add(k)
                dedup.append(w)
        self.wrappers = dedup

    def _collect_calls(self) -> None:
        for q, info in self.funcs.items():
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    callee = self._resolve_func_ref(node.func, q)
                    if callee and callee != q:
                        info.calls.add(callee)
                # Function references passed as arguments (e.g.
                # jax.lax.fori_loop(0, n, body, init)) keep the body
                # reachable too.
                if isinstance(node, ast.Call):
                    for arg in node.args:
                        if isinstance(arg, (ast.Name, ast.Attribute)):
                            ref = self._resolve_func_ref(arg, q)
                            if ref and ref != q:
                                info.calls.add(ref)

    # -- queries -------------------------------------------------------------
    def jit_roots(self) -> Set[str]:
        return {w.target for w in self.wrappers if w.target}

    def reachable_from_jit(self) -> Set[str]:
        """Qualnames reachable (BFS over module-local call edges) from any
        jit/pallas root — the set whose bodies trace into executables."""
        roots = self.jit_roots()
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            q = stack.pop()
            if q in seen or q not in self.funcs:
                continue
            seen.add(q)
            stack.extend(self.funcs[q].calls - seen)
        return seen

    def bound_wrappers(self) -> Dict[str, JitWrapper]:
        """{bound name: wrapper} for wrappers assigned to a name/attr —
        jitted call sites are calls through these names."""
        return {w.bound_name: w for w in self.wrappers if w.bound_name}


# --- whole-program graph (v2) ------------------------------------------------

HOST = "host"
DEVICE = "device"
UNKNOWN = "unknown"

_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.")
_HOST_BUILTINS = {
    "len", "range", "sum", "min", "max", "sorted", "list", "tuple", "dict",
    "set", "zip", "enumerate", "round", "abs", "str", "repr",
}


def gid(relpath: str, qualname: str) -> str:
    return f"{relpath}::{qualname}"


def split_gid(g: str) -> Tuple[str, str]:
    relpath, _, qualname = g.partition("::")
    return relpath, qualname


def module_name(relpath: str) -> str:
    """'dynamo_tpu/engine/scheduler.py' -> 'dynamo_tpu.engine.scheduler'."""
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


@dataclass
class ClassInfo:
    relpath: str
    name: str
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)       # dotted base refs
    methods: Dict[str, str] = field(default_factory=dict)  # method -> gid
    # self.<attr> typing discovered in the class body:
    attr_type: Dict[str, str] = field(default_factory=dict)  # attr -> class key
    attr_func: Dict[str, str] = field(default_factory=dict)  # attr -> gid
    # attr -> module relpaths, for ``self.model = get_module(cfg)`` where
    # the callee returns one of a finite set of scanned modules.
    attr_modules: Dict[str, Set[str]] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.relpath}::{self.name}"


class ProjectGraph:
    """Cross-module call graph over every module in a ``ProjectIndex``.

    Built in three passes: (1) collect defs/classes/imports per module,
    (2) type class attributes from constructor calls, annotations, and
    typed ``__init__`` params, (3) resolve every call site to a gid where
    possible and record edges. A final fixpoint pass classifies each
    function's return value as host/device/unknown for the sync rules.
    """

    def __init__(self, index: ProjectIndex) -> None:
        self.index = index
        self.graphs: Dict[str, ModuleGraph] = {}          # relpath -> ModuleGraph
        self.by_modname: Dict[str, SourceModule] = {}     # dotted name -> module
        self.imports: Dict[str, Dict[str, str]] = {}      # relpath -> alias -> dotted target
        self.funcs: Dict[str, FuncInfo] = {}              # gid -> FuncInfo
        self.classes: Dict[str, ClassInfo] = {}           # "relpath::Class" -> info
        self._class_by_name: Dict[str, List[str]] = {}    # Class -> [class keys]
        self.edges: Dict[str, Set[str]] = {}              # gid -> callee gids
        self._ret_class: Dict[str, str] = {}
        for mod in index.modules:
            self.graphs[mod.relpath] = ModuleGraph(mod)
            self.by_modname[module_name(mod.relpath)] = mod
            for q, info in self.graphs[mod.relpath].funcs.items():
                self.funcs[gid(mod.relpath, q)] = info
        for mod in index.modules:  # needs by_modname fully populated
            self.imports[mod.relpath] = self._collect_imports(mod)
        # gid -> module relpaths: functions whose every return is a scanned
        # module reference (the ``get_module(config)`` registry pattern).
        self.module_returners: Dict[str, Set[str]] = {}
        self._collect_module_returners()
        # gid -> {local var -> module relpaths} for vars bound from a
        # module-returner or a module alias.
        self.var_modules: Dict[str, Dict[str, Set[str]]] = {}
        self._collect_var_modules()
        self._collect_classes()
        self._type_class_attrs()
        self._collect_edges()

    # -- pass 1: imports ------------------------------------------------------
    def _collect_imports(self, mod: SourceModule) -> Dict[str, str]:
        out: Dict[str, str] = {}
        pkg = module_name(mod.relpath).rsplit(".", 1)[0] if "." in module_name(mod.relpath) else ""
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        out[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    # Relative import: climb from the containing package.
                    parts = module_name(mod.relpath).split(".")
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module] if node.module else []))
                elif pkg and base.split(".")[0] not in ("dynamo_tpu", "tools") and f"{pkg}.{base}" in self.by_modname:
                    # Implicit-relative style "from engine import x" (rare).
                    base = f"{pkg}.{base}"
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{base}.{a.name}" if base else a.name
        return out

    def _module_of_ref(self, relpath: str, name: str) -> Optional[str]:
        """Relpath of the scanned module a dotted reference names, if any
        (``llama`` via ``from .models import llama``, ``pkg.mod``, ...)."""
        if not name:
            return None
        imp = self.imports.get(relpath, {})
        head = name.split(".")[0]
        target = imp[head] + name[len(head):] if head in imp else name
        mod = self.by_modname.get(target)
        return mod.relpath if mod is not None else None

    def _collect_module_returners(self) -> None:
        for g, info in self.funcs.items():
            relpath, _ = split_gid(g)
            mods: Set[str] = set()
            ok = False
            for node in ast.walk(info.node):
                if not isinstance(node, ast.Return) or node.value is None:
                    continue
                if isinstance(node.value, (ast.Name, ast.Attribute)):
                    m = self._module_of_ref(relpath, dotted(node.value))
                    if m is not None:
                        mods.add(m)
                        ok = True
                        continue
                ok = False
                break
            if ok and mods:
                self.module_returners[g] = mods

    def _collect_var_modules(self) -> None:
        for g, info in self.funcs.items():
            relpath, q = split_gid(g)
            out: Dict[str, Set[str]] = {}
            for node in ast.walk(info.node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    continue
                var = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    callee = self._resolve_func(relpath, q, dotted(node.value.func))
                    if callee in self.module_returners:
                        out[var] = set(self.module_returners[callee])
                elif isinstance(node.value, (ast.Name, ast.Attribute)):
                    m = self._module_of_ref(relpath, dotted(node.value))
                    if m is not None:
                        out[var] = {m}
            if out:
                self.var_modules[g] = out

    # -- pass 2: classes + attribute typing -----------------------------------
    def _collect_classes(self) -> None:
        for mod in self.index.modules:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                info = ClassInfo(
                    relpath=mod.relpath, name=node.name, node=node,
                    bases=[dotted(b) for b in node.bases if dotted(b)],
                )
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info.methods[item.name] = gid(mod.relpath, f"{node.name}.{item.name}")
                self.classes[info.key] = info
                self._class_by_name.setdefault(node.name, []).append(info.key)

    def _resolve_class(self, relpath: str, name: str) -> Optional[str]:
        """Resolve a dotted class reference visible from ``relpath``."""
        if not name:
            return None
        local = f"{relpath}::{name}"
        if local in self.classes:
            return local
        imp = self.imports.get(relpath, {})
        head = name.split(".")[0]
        if head in imp:
            target = imp[head] + name[len(head):]
            modpath, _, clsname = target.rpartition(".")
            mod = self.by_modname.get(modpath)
            if mod is not None:
                key = f"{mod.relpath}::{clsname}"
                if key in self.classes:
                    return key
            # ``import pkg.mod`` then ``pkg.mod.Class``
            mod = self.by_modname.get(target.rpartition(".")[0])
        # Unique class name anywhere in the tree (last resort, unambiguous only).
        cands = self._class_by_name.get(name.rpartition(".")[2], [])
        if len(cands) == 1:
            return cands[0]
        return None

    def _resolve_func(self, relpath: str, scope: Optional[str], name: str) -> Optional[str]:
        """Resolve a dotted function reference from ``relpath``/``scope`` to
        a gid: local defs, imported functions, ``mod.f``, ``Class.m``."""
        if not name:
            return None
        graph = self.graphs[relpath]
        local = graph._resolve_func_ref(_name_node(name), scope)
        if local:
            return gid(relpath, local)
        imp = self.imports.get(relpath, {})
        head, _, rest = name.partition(".")
        if head in imp:
            target = imp[head] + (("." + rest) if rest else "")
            modpath, _, fname = target.rpartition(".")
            mod = self.by_modname.get(modpath)
            if mod is not None and fname in self.graphs[mod.relpath].funcs:
                return gid(mod.relpath, fname)
            # from x import Class; Class.m / Class(...)
            ck = self._resolve_class(relpath, head)
            if ck is not None:
                info = self.classes[ck]
                if rest in info.methods:
                    return info.methods[rest]
                if not rest:
                    return info.methods.get("__init__")
        # Class.m / Class(...) with a locally defined class.
        ck = self._resolve_class(relpath, head)
        if ck is not None:
            info = self.classes[ck]
            if rest and rest in info.methods:
                return info.methods[rest]
            if not rest and "__init__" in info.methods:
                return info.methods["__init__"]
        return None

    def _method_on(self, class_key: str, method: str, depth: int = 0) -> Optional[str]:
        """Method lookup with single-level (transitively capped) MRO walk."""
        info = self.classes.get(class_key)
        if info is None or depth > 4:
            return None
        if method in info.methods:
            return info.methods[method]
        for base in info.bases:
            bk = self._resolve_class(info.relpath, base)
            if bk is not None:
                hit = self._method_on(bk, method, depth + 1)
                if hit is not None:
                    return hit
        return None

    def _type_class_attrs(self) -> None:
        for key, info in self.classes.items():
            relpath = info.relpath
            ann_of_param: Dict[str, Dict[str, str]] = {}
            for item in info.node.body:
                if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                # param -> annotated class key (for `self.x = param`).
                pann: Dict[str, str] = {}
                for p in item.args.posonlyargs + item.args.args + item.args.kwonlyargs:
                    if p.annotation is not None:
                        aname = dotted(p.annotation)
                        if not aname and isinstance(p.annotation, ast.Constant) and isinstance(p.annotation.value, str):
                            aname = p.annotation.value
                        if not aname and isinstance(p.annotation, ast.Subscript):
                            # Optional[Scheduler] / "Optional[Scheduler]"
                            inner = dotted(p.annotation.slice)
                            aname = inner
                        ck = self._resolve_class(relpath, aname) if aname else None
                        if ck is not None:
                            pann[p.arg] = ck
                ann_of_param[item.name] = pann
                scope = f"{info.name}.{item.name}"
                for node in ast.walk(item):
                    tgt = None
                    if isinstance(node, ast.Assign) and len(node.targets) == 1:
                        tgt, val = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value is not None:
                        tgt, val = node.target, node.value
                    else:
                        continue
                    if not (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    attr = tgt.attr
                    if isinstance(val, ast.Call):
                        callee = dotted(val.func)
                        ck = self._resolve_class(relpath, callee)
                        if ck is not None:
                            info.attr_type.setdefault(attr, ck)
                            continue
                        # self.x = jax.jit(f): route calls through the attr
                        # to the wrapped function.
                        if callee in _JIT_CALLS | _PALLAS_CALLS and val.args:
                            fg = self._resolve_func(relpath, scope, dotted(val.args[0]))
                            if fg is not None:
                                info.attr_func.setdefault(attr, fg)
                            continue
                        # self.model = get_module(cfg): module-set typing.
                        cg = self._resolve_func(relpath, scope, callee)
                        if cg in self.module_returners:
                            info.attr_modules.setdefault(attr, set()).update(
                                self.module_returners[cg])
                            continue
                    ref = dotted(val)
                    if ref in pann:  # self.x = typed-param
                        info.attr_type.setdefault(attr, pann[ref])
                        continue
                    m = self._module_of_ref(relpath, ref) if ref else None
                    if m is not None:  # self.model = llama
                        info.attr_modules.setdefault(attr, {m})
                        continue
                    fg = self._resolve_func(relpath, scope, ref) if ref else None
                    if fg is not None:
                        info.attr_func.setdefault(attr, fg)
                # AnnAssign without value: `self.x: Scheduler`
                for node in ast.walk(item):
                    if (isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Attribute)
                            and isinstance(node.target.value, ast.Name)
                            and node.target.value.id == "self"):
                        aname = dotted(node.annotation)
                        ck = self._resolve_class(relpath, aname) if aname else None
                        if ck is not None:
                            info.attr_type.setdefault(node.target.attr, ck)

    # -- pass 3: call resolution ----------------------------------------------
    def resolve_call(self, relpath: str, scope: str, name: str) -> Optional[str]:
        """Resolve one dotted call-site name to a callee gid (or None).
        ``scope`` is the caller's qualname in ``relpath``."""
        if not name:
            return None
        cls_name = scope.rsplit(".", 2)[-2] if "." in scope else None
        class_key = f"{relpath}::{cls_name}" if cls_name else None
        if name.startswith(("self.", "cls.")):
            rest = name.split(".", 1)[1]
            if class_key and class_key in self.classes:
                head, _, tail = rest.partition(".")
                if not tail:
                    hit = self._method_on(class_key, head)
                    if hit is not None:
                        return hit
                    # class-attribute callable: self._f(...)
                    fg = self.classes[class_key].attr_func.get(head)
                    if fg is not None:
                        return fg
                else:
                    # self.attr.m(...): typed attribute dispatch.
                    ck = self.classes[class_key].attr_type.get(head)
                    if ck is not None:
                        return self._method_on(ck, tail.split(".")[0])
            return None
        # typed-parameter dispatch: p.m(...) where p: Class
        head, _, tail = name.partition(".")
        fn = self.funcs.get(gid(relpath, scope))
        if tail and fn is not None:
            for p in fn.node.args.posonlyargs + fn.node.args.args + fn.node.args.kwonlyargs:
                if p.arg == head and p.annotation is not None:
                    ck = self._resolve_class(relpath, dotted(p.annotation))
                    if ck is not None:
                        return self._method_on(ck, tail.split(".")[0])
        return self._resolve_func(relpath, scope, name)

    def resolve_call_multi(self, relpath: str, scope: str, name: str) -> Set[str]:
        """Like ``resolve_call`` but returns every candidate callee — the
        extra candidates come from module-set typed names (``model.decode``
        where ``model = get_module(cfg)`` may be any registry module)."""
        out: Set[str] = set()
        one = self.resolve_call(relpath, scope, name)
        if one is not None:
            out.add(one)
        head, _, tail = name.partition(".") if name else ("", "", "")
        if not tail:
            return out
        fname = tail.split(".")[0]
        mods: Set[str] = set()
        if head in ("self", "cls"):
            cls_name = scope.rsplit(".", 2)[-2] if "." in scope else None
            info = self.classes.get(f"{relpath}::{cls_name}") if cls_name else None
            attr, _, meth = tail.partition(".")
            if info is not None and meth:
                mods = info.attr_modules.get(attr, set())
                fname = meth.split(".")[0]
        else:
            mods = self.var_modules.get(gid(relpath, scope), {}).get(head, set())
        for m in mods:
            if fname in self.graphs[m].funcs:
                out.add(gid(m, fname))
        return out

    def _collect_edges(self) -> None:
        for relpath, graph in self.graphs.items():
            for q, info in graph.funcs.items():
                g = gid(relpath, q)
                out = self.edges.setdefault(g, set())
                for node in ast.walk(info.node):
                    if not isinstance(node, ast.Call):
                        continue
                    for callee in self.resolve_call_multi(relpath, q, dotted(node.func)):
                        if callee != g:
                            out.add(callee)
                    # function references passed as args stay reachable
                    for arg in list(node.args) + [k.value for k in node.keywords]:
                        if isinstance(arg, (ast.Name, ast.Attribute)):
                            ref = self.resolve_call(relpath, q, dotted(arg))
                            if ref and ref != g:
                                out.add(ref)

    # -- queries --------------------------------------------------------------
    def jit_roots(self) -> Set[str]:
        """Every jit/pallas wrapper target across the tree, with unresolved
        (cross-module) targets re-resolved project-wide."""
        roots: Set[str] = set()
        for relpath, graph in self.graphs.items():
            for w in graph.wrappers:
                if w.target:
                    roots.add(gid(relpath, w.target))
                elif w.target_lambda is not None:
                    # jit(lambda ...: model.decode(...)): every call in the
                    # lambda body traces into the executable.
                    scope = w.scope or "<module>"
                    for node in ast.walk(w.target_lambda.body):
                        if isinstance(node, ast.Call):
                            roots |= self.resolve_call_multi(
                                relpath, scope, dotted(node.func))
                elif w.target_dotted:
                    g = self._resolve_func(relpath, w.scope, w.target_dotted)
                    if g is None:
                        g = self.resolve_call(
                            relpath, w.scope or "<module>", w.target_dotted)
                    if g is not None:
                        roots.add(g)
        return roots

    def reachable(self, roots: Iterable[str]) -> Set[str]:
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.funcs]
        while stack:
            g = stack.pop()
            if g in seen:
                continue
            seen.add(g)
            stack.extend(self.edges.get(g, ()) - seen)
        return seen

    def reachable_from_jit(self) -> Set[str]:
        return self.reachable(self.jit_roots())

    # -- fixpoint return classification ---------------------------------------
    def _classify_primitive_call(self, relpath: str, scope: str, call: ast.Call) -> str:
        name = dotted(call.func)
        if not name:
            return UNKNOWN
        if name in ("jax.device_get", "device_get") or name.startswith(("np.", "numpy.")):
            return HOST
        if name in _HOST_BUILTINS or name.startswith(("time.", "os.", "math.", "json.")):
            return HOST
        if name.startswith(_DEVICE_PREFIXES):
            return DEVICE
        if name.split(".")[-1].endswith("_jit"):
            return DEVICE
        callee = self.resolve_call(relpath, scope, name)
        if callee is not None:
            return self._ret_class.get(callee, UNKNOWN)
        return UNKNOWN

    def _classify_return_expr(self, relpath: str, scope: str, expr: ast.AST) -> str:
        if expr is None or isinstance(expr, ast.Constant):
            return HOST
        if isinstance(expr, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                             ast.SetComp, ast.GeneratorExp, ast.JoinedStr, ast.Compare,
                             ast.BoolOp)):
            return HOST
        if isinstance(expr, ast.Tuple):
            kinds = {self._classify_return_expr(relpath, scope, e) for e in expr.elts}
            if DEVICE in kinds:
                return DEVICE
            if UNKNOWN in kinds:
                return UNKNOWN
            return HOST
        if isinstance(expr, ast.Call):
            return self._classify_primitive_call(relpath, scope, expr)
        if isinstance(expr, ast.BinOp):
            l = self._classify_return_expr(relpath, scope, expr.left)
            r = self._classify_return_expr(relpath, scope, expr.right)
            if DEVICE in (l, r):
                return DEVICE
            if UNKNOWN in (l, r):
                return UNKNOWN
            return HOST
        return UNKNOWN

    def infer_return_classes(self, max_iter: int = 8) -> Dict[str, str]:
        """{gid: host|device|unknown} for every function's return value,
        iterated to fixpoint so helper-through-helper device values are
        classified across module boundaries."""
        if self._ret_class:
            return self._ret_class
        self._ret_class = {g: UNKNOWN for g in self.funcs}
        for _ in range(max_iter):
            changed = False
            for g, info in self.funcs.items():
                relpath, q = split_gid(g)
                kinds: Set[str] = set()
                for node in ast.walk(info.node):
                    if isinstance(node, ast.Return):
                        kinds.add(self._classify_return_expr(relpath, q, node.value))
                if not kinds:
                    new = HOST  # no return statement -> returns None
                elif DEVICE in kinds:
                    new = DEVICE
                elif UNKNOWN in kinds:
                    new = UNKNOWN
                else:
                    new = HOST
                if new != self._ret_class[g]:
                    self._ret_class[g] = new
                    changed = True
            if not changed:
                break
        return self._ret_class


def _name_node(dotted_name: str) -> ast.AST:
    """Rebuild a Name/Attribute node from a dotted string (for reusing the
    module-local resolver on plain strings)."""
    parts = dotted_name.split(".")
    node: ast.AST = ast.Name(id=parts[0], ctx=ast.Load())
    for p in parts[1:]:
        node = ast.Attribute(value=node, attr=p, ctx=ast.Load())
    return node


_PROJECT_GRAPH_CACHE: Dict[int, ProjectGraph] = {}


def project_graph(index: ProjectIndex) -> ProjectGraph:
    """Memoized ProjectGraph per index: several rules share one build."""
    key = id(index)
    if key not in _PROJECT_GRAPH_CACHE:
        _PROJECT_GRAPH_CACHE.clear()  # one live index at a time
        _PROJECT_GRAPH_CACHE[key] = ProjectGraph(index)
    return _PROJECT_GRAPH_CACHE[key]
