"""Module-level call graph + jit/pallas root discovery.

Resolution is deliberately module-local and name-based: ``f(...)`` resolves
to a function defined in the same module, ``self.m(...)`` to a method of
the enclosing class. That covers how this codebase actually wires its jit
bodies (kernels and their helpers live beside their ``jax.jit`` /
``pallas_call`` sites) without pretending to be a type checker.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from tools.dtlint.core import SourceModule, dotted, iter_functions

_JIT_CALLS = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PALLAS_CALLS = {"pl.pallas_call", "pallas_call", "jax.experimental.pallas.pallas_call"}
_PARTIAL = {"partial", "functools.partial"}


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST
    cls: Optional[str]            # enclosing class name, if a method
    calls: Set[str] = field(default_factory=set)   # resolved callee qualnames


@dataclass
class JitWrapper:
    """One ``jax.jit(fn, ...)`` / ``@jax.jit`` / ``pallas_call(kernel)``
    site: the wrapped function (when resolvable), the name the wrapper is
    bound to (module global or ``self.X`` attribute), and donation info."""

    target: Optional[str]          # wrapped function qualname, if resolved
    bound_name: Optional[str]      # "name" or "self.attr" the wrapper binds to
    line: int
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    kind: str = "jit"              # "jit" | "pallas"


class ModuleGraph:
    """Call graph + jit roots for ONE module."""

    def __init__(self, mod: SourceModule) -> None:
        self.mod = mod
        self.funcs: Dict[str, FuncInfo] = {}
        self.wrappers: List[JitWrapper] = []
        self._collect_funcs()
        self._collect_wrappers()
        self._collect_calls()

    # -- collection ----------------------------------------------------------
    def _collect_funcs(self) -> None:
        for q, fn in iter_functions(self.mod.tree):
            cls = q.rsplit(".", 2)[-2] if "." in q else None
            self.funcs[q] = FuncInfo(qualname=q, node=fn, cls=cls)

    def _resolve_func_ref(self, node: ast.AST, scope: Optional[str]) -> Optional[str]:
        """Resolve a function reference (Name / self.attr) to a qualname
        defined in this module. ``scope`` is the enclosing qualname prefix
        used to find nested defs and sibling methods."""
        name = dotted(node)
        if not name:
            return None
        if name.startswith("self."):
            attr = name[len("self."):]
            if scope and "." in scope:
                cls = scope.rsplit(".", 1)[0]
                cand = f"{cls}.{attr}"
                if cand in self.funcs:
                    return cand
            return None
        # nested def in the same scope wins, then module-level
        if scope:
            cand = f"{scope}.{name}"
            if cand in self.funcs:
                return cand
        if name in self.funcs:
            return name
        return None

    @staticmethod
    def _int_tuple(node: Optional[ast.AST]) -> Tuple[int, ...]:
        if node is None:
            return ()
        try:
            v = ast.literal_eval(node)
        except (ValueError, SyntaxError):
            return ()
        if isinstance(v, int):
            return (v,)
        if isinstance(v, (tuple, list)):
            return tuple(x for x in v if isinstance(x, int))
        return ()

    @staticmethod
    def _str_tuple(node: Optional[ast.AST]) -> Tuple[str, ...]:
        if node is None:
            return ()
        try:
            v = ast.literal_eval(node)
        except (ValueError, SyntaxError):
            return ()
        if isinstance(v, str):
            return (v,)
        if isinstance(v, (tuple, list)):
            return tuple(x for x in v if isinstance(x, str))
        return ()

    def _wrapper_from_call(
        self, call: ast.Call, scope: Optional[str], bound: Optional[str]
    ) -> Optional[JitWrapper]:
        callee = dotted(call.func)
        kind = None
        if callee in _JIT_CALLS:
            kind = "jit"
        elif callee in _PALLAS_CALLS:
            kind = "pallas"
        elif callee in _PARTIAL and call.args:
            inner = dotted(call.args[0])
            if inner in _JIT_CALLS:
                # partial(jax.jit, static_argnums=...) used as a decorator
                kw = {k.arg: k.value for k in call.keywords if k.arg}
                return JitWrapper(
                    target=None, bound_name=bound, line=call.lineno,
                    static_argnums=self._int_tuple(kw.get("static_argnums")),
                    static_argnames=self._str_tuple(kw.get("static_argnames")),
                    donate_argnums=self._int_tuple(kw.get("donate_argnums")),
                    donate_argnames=self._str_tuple(kw.get("donate_argnames")),
                )
            return None
        if kind is None:
            return None
        target = self._resolve_func_ref(call.args[0], scope) if call.args else None
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        return JitWrapper(
            target=target, bound_name=bound, line=call.lineno, kind=kind,
            static_argnums=self._int_tuple(kw.get("static_argnums")),
            static_argnames=self._str_tuple(kw.get("static_argnames")),
            donate_argnums=self._int_tuple(kw.get("donate_argnums")),
            donate_argnames=self._str_tuple(kw.get("donate_argnames")),
        )

    def _collect_wrappers(self) -> None:
        # Decorated defs: @jax.jit, @partial(jax.jit, ...), @pl.pallas_call(...)
        for q, info in self.funcs.items():
            for dec in getattr(info.node, "decorator_list", []):
                w = None
                name = dotted(dec)
                if name in _JIT_CALLS:
                    w = JitWrapper(target=q, bound_name=q, line=dec.lineno)
                elif isinstance(dec, ast.Call):
                    w = self._wrapper_from_call(dec, None, q)
                    if w is not None:
                        w.target = q
                if w is not None:
                    self.wrappers.append(w)

        # Call-expression wrappers anywhere: x = jax.jit(f, ...) /
        # self._f_jit = jax.jit(f) / res = pl.pallas_call(kernel, ...)(args)
        line_scope = {}
        for q, info in self.funcs.items():
            end = getattr(info.node, "end_lineno", info.node.lineno)
            for ln in range(info.node.lineno, end + 1):
                line_scope[ln] = q

        class V(ast.NodeVisitor):
            def __init__(v):
                v.out: List[JitWrapper] = []

            def visit_Assign(v, node: ast.Assign):
                if isinstance(node.value, ast.Call):
                    scope = line_scope.get(node.lineno)
                    bound = dotted(node.targets[0]) if len(node.targets) == 1 else None
                    w = self._wrapper_from_call(node.value, scope, bound)
                    if w is not None:
                        v.out.append(w)
                        return
                v.generic_visit(node)

            def visit_Call(v, node: ast.Call):
                scope = line_scope.get(node.lineno)
                w = self._wrapper_from_call(node, scope, None)
                if w is not None:
                    v.out.append(w)
                v.generic_visit(node)

        vis = V()
        vis.visit(self.mod.tree)
        # De-dup (an Assign's Call is visited twice).
        seen = set()
        for w in vis.out + self.wrappers:
            k = (w.line, w.bound_name, w.target)
            if k not in seen:
                seen.add(k)
        dedup: List[JitWrapper] = []
        seen = set()
        for w in self.wrappers + vis.out:
            k = (w.line, w.bound_name, w.target, w.kind)
            if k not in seen:
                seen.add(k)
                dedup.append(w)
        self.wrappers = dedup

    def _collect_calls(self) -> None:
        for q, info in self.funcs.items():
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    callee = self._resolve_func_ref(node.func, q)
                    if callee and callee != q:
                        info.calls.add(callee)
                # Function references passed as arguments (e.g.
                # jax.lax.fori_loop(0, n, body, init)) keep the body
                # reachable too.
                if isinstance(node, ast.Call):
                    for arg in node.args:
                        if isinstance(arg, (ast.Name, ast.Attribute)):
                            ref = self._resolve_func_ref(arg, q)
                            if ref and ref != q:
                                info.calls.add(ref)

    # -- queries -------------------------------------------------------------
    def jit_roots(self) -> Set[str]:
        return {w.target for w in self.wrappers if w.target}

    def reachable_from_jit(self) -> Set[str]:
        """Qualnames reachable (BFS over module-local call edges) from any
        jit/pallas root — the set whose bodies trace into executables."""
        roots = self.jit_roots()
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            q = stack.pop()
            if q in seen or q not in self.funcs:
                continue
            seen.add(q)
            stack.extend(self.funcs[q].calls - seen)
        return seen

    def bound_wrappers(self) -> Dict[str, JitWrapper]:
        """{bound name: wrapper} for wrappers assigned to a name/attr —
        jitted call sites are calls through these names."""
        return {w.bound_name: w for w in self.wrappers if w.bound_name}
