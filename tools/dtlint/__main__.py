"""CLI: ``python -m tools.dtlint [paths...]``.

Exit codes: 0 = clean (modulo baseline), 1 = findings or stale baseline
entries, 2 = usage/config error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from tools.dtlint.core import LintConfig, RULE_DOCS, run_lint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dtlint",
        description="static invariant checker (jit hygiene, sync points, "
                    "donation, metrics drift, thread safety)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to scan (default: dynamo_tpu)")
    p.add_argument("--rule", action="append", default=None, metavar="RULE",
                   help="run only this rule (repeatable, or comma-separated)")
    p.add_argument("--baseline", default="dtlint_baseline.json",
                   help="baseline file of reviewed findings (default: "
                        "dtlint_baseline.json; '' disables)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON on stdout")
    p.add_argument("--root", default=os.getcwd(), help=argparse.SUPPRESS)
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    args = p.parse_args(argv)

    # Importing the rule modules populates the registry for --list-rules.
    from tools.dtlint import rules_jit, rules_metrics, rules_sync, rules_threads  # noqa: F401

    if args.list_rules:
        for name in sorted(RULE_DOCS):
            print(f"{name}  {RULE_DOCS[name]}")
        return 0

    rules = None
    if args.rule:
        rules = []
        for r in args.rule:
            rules.extend(x.strip() for x in r.split(",") if x.strip())

    config = LintConfig(
        root=args.root,
        paths=tuple(args.paths) if args.paths else ("dynamo_tpu",),
    )
    baseline = None
    if args.baseline:
        baseline = (args.baseline if os.path.isabs(args.baseline)
                    else os.path.join(args.root, args.baseline))
    try:
        result = run_lint(config, rules=rules, baseline_path=baseline)
    except ValueError as e:
        print(f"dtlint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "stale_baseline": result.stale_baseline,
            "baseline_size": result.baseline_size,
            "ok": result.ok,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        for e in result.stale_baseline:
            print(f"{e['file']}: STALE-BASELINE [{e['rule']}/{e['qualname']}/"
                  f"{e['key']}] no longer matches a finding — remove the "
                  f"entry (reason was: {e['reason']})")
        n = len(result.findings)
        print(f"dtlint: {n} finding{'s' if n != 1 else ''}, "
              f"{len(result.stale_baseline)} stale baseline entr"
              f"{'ies' if len(result.stale_baseline) != 1 else 'y'} "
              f"(baseline: {result.baseline_size})", file=sys.stderr)
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
