"""CLI: ``python -m tools.dtlint [paths...]``.

Exit codes: 0 = clean (modulo baseline), 1 = findings or stale baseline
entries, 2 = usage/config error.

Modes beyond the plain run:

- ``--diff``: pre-commit mode. The whole-program analysis still runs over
  the full tree (cross-module rules are meaningless on a file subset), but
  findings are *reported* only for files changed vs git HEAD — except for
  global rules whose anchor files changed (touch the Grafana dashboard and
  every MET001 finding is in play; touch a wire writer and all of WIRE001
  is), and any change under tools/dtlint/ itself, which reports everything.
- ``--github``: emit GitHub Actions ``::error file=...,line=...`` workflow
  annotations. With ``--from-json FILE`` it annotates from a prior
  ``--json`` dump without re-linting (the lint step already failed the
  job; the annotation step just decorates the diff) and always exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Set

from tools.dtlint.core import LintConfig, RULE_DOCS, run_lint


def _changed_files(root: str) -> Set[str]:
    """Repo-relative paths changed vs HEAD (staged + unstaged + untracked)."""
    out: Set[str] = set()
    for cmd in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                cmd, cwd=root, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if res.returncode == 0:
            out.update(l.strip() for l in res.stdout.splitlines() if l.strip())
    return out


def _global_anchor_map(config: LintConfig) -> Dict[str, Set[str]]:
    """rule -> anchor files whose change puts the rule's whole finding set
    in play (cross-file rules relate a changed anchor to unchanged sites)."""
    wire_paths = {e.partition("::")[0]
                  for e in (config.wire_writers + config.wire_readers
                            + config.wire_stop_writers + config.wire_stop_readers)}
    return {
        "MET001": {config.aggregator_path, config.grafana_path},
        "SYNC001": {config.sync_allowlist_path},
        "WARM001": set(config.warmup_scopes),
        "WIRE001": wire_paths | {config.aggregator_path, config.mocker_path},
    }


def _github_line(f: dict) -> str:
    # Annotation messages are single-line; commas/newlines survive but keep
    # it tidy. The title carries the rule so the annotation list scans well.
    msg = str(f.get("message", "")).replace("\n", " ")
    return (f"::error file={f['file']},line={f['line']},"
            f"title=dtlint {f['rule']}::[{f.get('qualname', '?')}] {msg}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dtlint",
        description="static invariant checker (jit hygiene, sync points, "
                    "donation, metrics drift, thread safety, warmup "
                    "coverage, async safety, KV leaks, wire drift)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to scan (default: dynamo_tpu)")
    p.add_argument("--rule", action="append", default=None, metavar="RULE",
                   help="run only this rule (repeatable, or comma-separated)")
    p.add_argument("--baseline", default="dtlint_baseline.json",
                   help="baseline file of reviewed findings (default: "
                        "dtlint_baseline.json; '' disables)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON on stdout")
    p.add_argument("--diff", action="store_true",
                   help="report only findings in files changed vs git HEAD "
                        "(global rules stay armed when their anchors "
                        "changed); the analysis itself is whole-tree")
    p.add_argument("--github", action="store_true",
                   help="emit GitHub Actions ::error annotations")
    p.add_argument("--from-json", default=None, metavar="FILE",
                   help="with --github: annotate from a prior --json dump "
                        "instead of re-linting (always exits 0)")
    p.add_argument("--root", default=os.getcwd(), help=argparse.SUPPRESS)
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    args = p.parse_args(argv)

    # Importing the rule modules populates the registry for --list-rules.
    from tools.dtlint import (  # noqa: F401
        rules_async, rules_jit, rules_leak, rules_metrics, rules_sync,
        rules_threads, rules_warmup, rules_wire,
    )

    if args.list_rules:
        for name in sorted(RULE_DOCS):
            print(f"{name}  {RULE_DOCS[name]}")
        return 0

    if args.from_json and not args.github:
        print("dtlint: --from-json requires --github", file=sys.stderr)
        return 2

    if args.github and args.from_json:
        try:
            with open(args.from_json) as fh:
                dump = json.load(fh)
        except (OSError, ValueError) as e:
            print(f"dtlint: cannot read {args.from_json}: {e}", file=sys.stderr)
            return 2
        for f in dump.get("findings", []):
            print(_github_line(f))
        for e in dump.get("stale_baseline", []):
            print(f"::error file={e['file']},title=dtlint stale baseline::"
                  f"[{e['rule']}/{e['qualname']}/{e['key']}] entry no longer "
                  f"matches a finding — remove it (reason was: {e['reason']})")
        return 0

    rules = None
    if args.rule:
        rules = []
        for r in args.rule:
            rules.extend(x.strip() for x in r.split(",") if x.strip())

    config = LintConfig(
        root=args.root,
        paths=tuple(args.paths) if args.paths else ("dynamo_tpu",),
    )
    baseline = None
    if args.baseline:
        baseline = (args.baseline if os.path.isabs(args.baseline)
                    else os.path.join(args.root, args.baseline))
    try:
        result = run_lint(config, rules=rules, baseline_path=baseline)
    except ValueError as e:
        print(f"dtlint: {e}", file=sys.stderr)
        return 2

    findings = result.findings
    stale = result.stale_baseline
    if args.diff:
        changed = _changed_files(args.root)
        if any(c.startswith("tools/dtlint/") or c == "dtlint_baseline.json"
               for c in changed):
            pass  # the checker itself changed: everything is in play
        else:
            anchors = _global_anchor_map(config)
            armed = {r for r, files in anchors.items() if files & changed}
            findings = [f for f in findings
                        if f.file in changed or f.rule in armed]
            # Stale baseline entries always report: they mean the tree moved
            # under the baseline, whatever file this commit touches.

    ok = not findings and not stale

    if args.github:
        for f in findings:
            print(_github_line(f.to_json()))
        for e in stale:
            print(f"::error file={e['file']},title=dtlint stale baseline::"
                  f"[{e['rule']}/{e['qualname']}/{e['key']}] entry no longer "
                  f"matches a finding — remove it (reason was: {e['reason']})")
    elif args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in findings],
            "stale_baseline": stale,
            "baseline_size": result.baseline_size,
            "ok": ok,
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"{e['file']}: STALE-BASELINE [{e['rule']}/{e['qualname']}/"
                  f"{e['key']}] no longer matches a finding — remove the "
                  f"entry (reason was: {e['reason']})")
        n = len(findings)
        print(f"dtlint: {n} finding{'s' if n != 1 else ''}, "
              f"{len(stale)} stale baseline entr"
              f"{'ies' if len(stale) != 1 else 'y'} "
              f"(baseline: {result.baseline_size})", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
