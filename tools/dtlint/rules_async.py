"""ASYNC001 — blocking calls reachable from serving-path ``async def``s.

The frontend, router, component endpoints, health plane, and fleet planner
all share one event loop per process; a single blocking call anywhere in an
``async def``'s synchronous call closure stalls *every* in-flight request
on that loop — the failure mode is invisible under light load and a
latency cliff under real traffic. The rule walks the whole-program call
graph (v2) from every ``async def`` in the configured serving scopes and
flags:

- ``time.sleep`` (use ``asyncio.sleep``),
- sync network IO (``subprocess.*``, ``urllib.request.urlopen``,
  ``requests.*``, ``socket.create_connection/create_server``,
  sock ``.accept()/.connect()``),
- un-timeouted ``lock.acquire()`` (a contended lock parks the loop),
- SYNC001-class device syncs (``block_until_ready``, ``jax.device_get``)
  — a device sync on the event loop serializes the loop against the TPU,
- bare ``open()`` directly in the async body (file IO off the loop).

Call edges through ``asyncio.to_thread``/``run_in_executor``/
``Thread(target=...)``/executor ``submit`` are NOT followed: work handed
to a thread is the sanctioned way to block. Nested ``def``s inside an
async body are likewise skipped at the top level (they are scanned only
if actually called on the loop).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.dtlint.callgraph import gid, project_graph, split_gid
from tools.dtlint.core import Finding, ProjectIndex, dotted, rule

_OFFLOADERS_EXACT = {"asyncio.to_thread", "threading.Thread", "Thread"}
_OFFLOADERS_TAIL = {"run_in_executor", "submit", "start_soon", "to_thread"}

_BLOCKING_EXACT = {
    "time.sleep": "time.sleep() parks the event loop — use asyncio.sleep()",
    "urllib.request.urlopen": "sync HTTP on the event loop",
    "socket.create_connection": "sync socket connect on the event loop",
    "_socket.create_connection": "sync socket connect on the event loop",
    "socket.create_server": "sync socket bind/listen on the event loop",
    "_socket.create_server": "sync socket bind/listen on the event loop",
    "jax.device_get": "device sync on the event loop serializes loop against device",
}
_BLOCKING_PREFIXES = {
    "subprocess.": "sync subprocess call on the event loop",
    "requests.": "sync HTTP (requests) on the event loop",
}
_SOCK_METHODS = {"accept", "connect", "recv", "recvfrom", "sendall"}
_LOCKISH = ("lock", "_lk", "sem", "mutex", "cond")


def _shallow_walk(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested def bodies."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _blocking_calls(fn: ast.AST, direct_async: bool) -> List[Tuple[int, str, str]]:
    """(line, call, why) blocking calls at this function's own depth."""
    out: List[Tuple[int, str, str]] = []
    for node in _shallow_walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        if not name:
            continue
        tail = name.split(".")[-1]
        recv = name.rsplit(".", 1)[0].lower() if "." in name else ""
        if name in _BLOCKING_EXACT:
            out.append((node.lineno, name, _BLOCKING_EXACT[name]))
            continue
        hit = False
        for pre, why in _BLOCKING_PREFIXES.items():
            if name.startswith(pre):
                out.append((node.lineno, name, why))
                hit = True
                break
        if hit:
            continue
        if tail == "block_until_ready":
            out.append((node.lineno, name,
                        "device sync on the event loop serializes loop against device"))
        elif tail == "sleep" and name.split(".")[0] not in ("asyncio", "anyio", "trio"):
            if name == "sleep" or recv in ("time",):
                out.append((node.lineno, name, "blocking sleep on the event loop"))
        elif tail in _SOCK_METHODS and any(s in recv for s in ("sock", "conn")):
            out.append((node.lineno, name, "sync socket IO on the event loop"))
        elif tail == "acquire" and any(s in recv for s in _LOCKISH):
            kw = {k.arg for k in node.keywords}
            has_nonblocking = "timeout" in kw or "blocking" in kw or node.args
            if not has_nonblocking:
                out.append((node.lineno, name,
                            "un-timeouted lock.acquire() can park the loop "
                            "indefinitely — pass timeout= or use an asyncio lock"))
        elif name == "open" and direct_async:
            out.append((node.lineno, name,
                        "sync file IO directly in an async body — offload via "
                        "asyncio.to_thread or read outside the handler"))
    return out


def _loop_edges(pg, relpath: str, q: str) -> Set[str]:
    """Call edges that stay ON the event loop: like the v2 graph's edges
    but skipping anything routed through a thread/executor offloader."""
    info = pg.funcs.get(gid(relpath, q))
    if info is None:
        return set()
    out: Set[str] = set()
    for node in _shallow_walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if name in _OFFLOADERS_EXACT or tail in _OFFLOADERS_TAIL:
            continue  # args run on a thread, not the loop
        out |= pg.resolve_call_multi(relpath, q, name)
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                out |= pg.resolve_call_multi(relpath, q, dotted(arg))
    # nested defs called at this depth are already resolved above; thread
    # targets were skipped with their offloader call.
    return out


@rule("ASYNC001", "blocking calls (sleep/sync IO/un-timeouted acquire/device syncs) reachable from serving-path async defs")
def async001(index: ProjectIndex) -> List[Finding]:
    cfg = index.config
    pg = project_graph(index)

    roots: List[str] = []
    for mod in index.modules:
        if not any(s in mod.relpath for s in cfg.async_scopes):
            continue
        for g, info in pg.graphs[mod.relpath].funcs.items():
            if isinstance(info.node, ast.AsyncFunctionDef):
                roots.append(gid(mod.relpath, g))
    if not roots:
        return []

    # BFS over on-loop edges only.
    seen: Set[str] = set()
    stack = list(roots)
    while stack:
        g = stack.pop()
        if g in seen or g not in pg.funcs:
            continue
        seen.add(g)
        relpath, q = split_gid(g)
        stack.extend(_loop_edges(pg, relpath, q) - seen)

    root_set = set(roots)
    findings: List[Finding] = []
    emitted: Set[Tuple[str, int, str]] = set()
    for g in sorted(seen):
        relpath, q = split_gid(g)
        mod = index.module(relpath)
        if mod is None:
            continue
        info = pg.funcs[g]
        direct_async = g in root_set or isinstance(info.node, ast.AsyncFunctionDef)
        for line, call, why in _blocking_calls(info.node, direct_async):
            if (relpath, line, call) in emitted:
                continue
            if mod.suppressed("ASYNC001", line):
                continue
            emitted.add((relpath, line, call))
            findings.append(Finding(
                "ASYNC001", relpath, line, q,
                f"{call}() reachable from a serving-path async def — {why}",
                key=f"blocking:{call}",
            ))
    return findings
