"""LEAK001 — KV block lifecycle: every exit from the live set must free.

The chaos suite proves allocator balance *dynamically* for the 24 fault
scenarios it scripts; this is the static form. In any class that owns a
block allocator (a ``self.<alloc>.allocate(...)`` caller), a sequence
leaving the live set without its blocks being released is a permanent KV
leak — the pool shrinks until admission stalls. Two checks:

- **(a) discarded allocation**: a bare expression-statement
  ``self.allocator.allocate(n)`` throws away the returned block ids — the
  blocks are live in the allocator's accounting but unreachable from any
  sequence, unfreeable forever.
- **(b) removal without release**: a method that removes a sequence from a
  *live* container (``running``/``active``/``live``/``inflight``) must
  reach an allocator ``release``/``free`` somewhere in its call closure
  (finish, deadline sweep, preemption, drain, migration export all do).
  Removals from *queued* containers (``waiting``/``pending``/``queued``)
  additionally pass if the closure promotes the sequence into another
  container (admission's waiting→running move) — queued sequences may
  hold prefix-cached blocks, so a reap from waiting still frees.

Exception paths: the closure check covers every named exit the scheduler
has; a release inside a ``finally``/``except`` body counts like any other.
What the rule cannot see — conditional leaks where release exists in the
closure but a branch skips it — stays the chaos suite's job; the rule
keeps the *structural* invariant (every exit path has a free in reach).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.dtlint.callgraph import gid, project_graph, split_gid
from tools.dtlint.core import (
    Finding, ProjectIndex, dotted, enclosing_map, qualname_at, rule,
)

_LIVE_CONTAINERS = {"running", "active", "live", "inflight", "in_flight", "sequences"}
_QUEUED_CONTAINERS = {"waiting", "pending", "queued"}
_REMOVERS = {"remove", "pop", "popleft", "discard"}
_RELEASERS = {"release", "free", "release_blocks", "free_blocks"}
_PROMOTERS = {"append", "insert", "appendleft", "add"}


def _alloc_attr(name: str) -> bool:
    return "alloc" in name.lower()


def _owning_classes(mod) -> Dict[str, ast.ClassDef]:
    """Classes in a module that call ``self.<alloc>.allocate(...)``."""
    out: Dict[str, ast.ClassDef] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "allocate"):
                base = dotted(sub.func.value)
                if base.startswith("self.") and _alloc_attr(base):
                    out[node.name] = node
                    break
    return out


def _closure_has(pg, index: ProjectIndex, root: str,
                 pred, max_nodes: int = 400) -> bool:
    """True if any function in ``root``'s call closure satisfies ``pred``
    (pred takes the function's ast node)."""
    seen: Set[str] = set()
    stack = [root]
    while stack and len(seen) < max_nodes:
        g = stack.pop()
        if g in seen or g not in pg.funcs:
            continue
        seen.add(g)
        if pred(pg.funcs[g].node):
            return True
        stack.extend(pg.edges.get(g, ()) - seen)
    return False


def _has_release(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr in _RELEASERS):
            base = dotted(node.func.value)
            if _alloc_attr(base) or base.startswith("self."):
                return True
    return False


def _has_promote(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr in _PROMOTERS):
            base = dotted(node.func.value)
            tail = base.split(".")[-1]
            if base.startswith("self.") and (
                tail in _LIVE_CONTAINERS or tail in _QUEUED_CONTAINERS
            ):
                return True
    return False


@rule("LEAK001", "allocator acquires that can leave the live set without a release on some exit path")
def leak001(index: ProjectIndex) -> List[Finding]:
    pg = project_graph(index)
    findings: List[Finding] = []
    for mod in index.modules:
        owners = _owning_classes(mod)
        if not owners:
            continue
        line_map = enclosing_map(mod.tree)
        for cls_name, cls in owners.items():
            for node in ast.walk(cls):
                # (a) allocation result discarded.
                if (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and node.value.func.attr == "allocate"):
                    base = dotted(node.value.func.value)
                    if base.startswith("self.") and _alloc_attr(base):
                        if not mod.suppressed("LEAK001", node.lineno):
                            findings.append(Finding(
                                "LEAK001", mod.relpath, node.lineno,
                                qualname_at(line_map, node.lineno),
                                f"return value of {base}.allocate() discarded — "
                                f"the blocks are unreachable and can never be "
                                f"released (permanent pool shrink)",
                                key="discarded-allocate",
                            ))
                    continue
                # (b) live-set removal without a release in reach.
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _REMOVERS):
                    continue
                base = dotted(node.func.value)
                if not base.startswith("self."):
                    continue
                container = base.split(".")[-1]
                live = container in _LIVE_CONTAINERS
                queued = container in _QUEUED_CONTAINERS
                if not live and not queued:
                    continue
                q = qualname_at(line_map, node.lineno)
                root = gid(mod.relpath, q)
                ok = _closure_has(pg, index, root, _has_release)
                if not ok and queued:
                    ok = _closure_has(pg, index, root, _has_promote)
                if ok or mod.suppressed("LEAK001", node.lineno):
                    continue
                findings.append(Finding(
                    "LEAK001", mod.relpath, node.lineno, q,
                    f"sequence removed from self.{container} but no allocator "
                    f"release/free is reachable from {q}() — blocks leak on "
                    f"this exit path",
                    key=f"no-release:{container}",
                ))
    return findings
