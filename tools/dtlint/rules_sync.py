"""SYNC001 — blocking device syncs inside scheduler/engine hot paths.

PR 4's zero-bubble pipeline rests on ONE invariant: a steady-state decode
step performs exactly one blocking host↔device sync (the previous step's
sampled-token readback). Every extra ``np.asarray``/``float()``/
``.item()``/``jax.device_get``/``.block_until_ready()`` on a device value
re-serializes the host against the device and reopens the bubble — and
the regression is invisible until a bench round measures the gap.

The rule scopes to the hot-path functions named in
``tools/dtlint/sync_allowlist.json`` and classifies every local name as
HOST / DEVICE / UNKNOWN with a small per-function taint pass:

- DEVICE: results of ``jnp.*``/``jax.*`` calls (except ``device_get``),
  calls through ``*_jit`` wrappers, params annotated ``jax.Array``.
- HOST: ``np.*`` results, literals/displays/comprehensions, ``len``,
  ``time.*``, ``jax.device_get`` results, params annotated with host
  types (int/float/bool/str/List/...).
- UNKNOWN: everything else (``self._pipe["sampled"]``, helper returns).

``block_until_ready``/``device_get`` always flag; ``np.asarray``/
``np.array`` flag on DEVICE **and UNKNOWN** arguments (guilty until
proven host — in these few functions a wrongly-accused host copy is a
one-line allowlist entry, a missed device sync is a perf regression);
``float``/``int``/``.item``/``.tolist`` flag on DEVICE only.

The allowlist file names each *sanctioned* sync — (file, func, call) with
a role and a reason. The ``role: "per_step"`` entries are the statically
declared 1-sync-per-step budget; ``bench.py`` cross-validates them
against the measured blocking-sync count (static and dynamic views of
the same invariant must agree).
"""

from __future__ import annotations

import ast
import json
import os
from typing import Callable, Dict, List, Optional

from tools.dtlint.callgraph import project_graph
from tools.dtlint.core import Finding, ProjectIndex, dotted, iter_functions, rule

HOST = "host"
DEVICE = "device"
UNKNOWN = "unknown"

_ALWAYS_SYNC = {"block_until_ready"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_COPYING = {"np.asarray", "np.array", "numpy.asarray", "numpy.array", "onp.asarray"}
_NARROWING = {"float", "int", "bool"}
_NARROWING_METHODS = {"item", "tolist"}

_HOST_ANN = {"int", "float", "bool", "str", "bytes", "list", "dict", "set",
             "tuple", "optional", "sequence", "iterable", "callable"}
_DEVICE_ANN_HINTS = ("jax.array", "jnp.ndarray", "jax.numpy", "array")


def load_sync_config(path: str) -> dict:
    if not os.path.exists(path):
        return {"hot_paths": {}, "allowed_syncs": []}
    with open(path) as f:
        return json.load(f)


def _classify_call(call: ast.Call) -> str:
    name = dotted(call.func)
    if not name:
        return UNKNOWN
    if name in _DEVICE_GET or name in _COPYING or name.startswith("np."):
        return HOST
    if name in ("len", "range", "sum", "min", "max", "sorted", "list", "tuple",
                "dict", "set", "zip", "enumerate", "round", "abs"):
        return HOST
    if name.startswith(("time.", "os.", "math.")):
        return HOST
    if name.startswith(("jnp.", "jax.", "lax.")):
        return DEVICE
    if name.split(".")[-1].endswith("_jit"):
        return DEVICE
    return UNKNOWN


def _classify_expr(expr: ast.AST, taint: Dict[str, str],
                   call_cls: Callable[[ast.Call], str] = _classify_call) -> str:
    if isinstance(expr, ast.Constant):
        return HOST
    if isinstance(expr, (ast.List, ast.Tuple, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp, ast.GeneratorExp, ast.JoinedStr)):
        return HOST
    if isinstance(expr, ast.Call):
        return call_cls(expr)
    if isinstance(expr, ast.Name):
        return taint.get(expr.id, UNKNOWN)
    if isinstance(expr, ast.Subscript):
        return _classify_expr(expr.value, taint, call_cls)
    if isinstance(expr, ast.BinOp):
        l = _classify_expr(expr.left, taint, call_cls)
        r = _classify_expr(expr.right, taint, call_cls)
        if DEVICE in (l, r):
            return DEVICE
        if UNKNOWN in (l, r):
            return UNKNOWN
        return HOST
    if isinstance(expr, ast.Compare) or isinstance(expr, ast.BoolOp):
        return HOST
    if isinstance(expr, ast.Attribute):
        # self.cache.k and friends: resident device buffers.
        base = dotted(expr)
        if ".cache." in f".{base}." or base.endswith((".k", ".v")):
            return DEVICE if base.startswith("self.") else UNKNOWN
        return UNKNOWN
    return UNKNOWN


def _ann_class(ann: Optional[ast.AST]) -> str:
    if ann is None:
        return UNKNOWN
    name = dotted(ann)
    if not name and isinstance(ann, ast.Subscript):
        name = dotted(ann.value)
    if not name and isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value
    low = (name or "").lower()
    if any(h in low for h in _DEVICE_ANN_HINTS):
        return DEVICE
    if low.split(".")[-1] in _HOST_ANN:
        return HOST
    return UNKNOWN


def _taint_function(fn: ast.AST,
                    call_cls: Callable[[ast.Call], str] = _classify_call) -> Dict[str, str]:
    taint: Dict[str, str] = {}
    a = fn.args
    for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
        taint[p.arg] = _ann_class(p.annotation)
    # Two passes: later assignments may reference earlier names.
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                cls = _classify_expr(node.value, taint, call_cls)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        taint[tgt.id] = cls
                    elif isinstance(tgt, ast.Tuple):
                        for el in tgt.elts:
                            if isinstance(el, ast.Name):
                                taint[el.id] = cls
            elif isinstance(node, ast.For) and isinstance(node.target, ast.Name):
                taint.setdefault(node.target.id, HOST)
    return taint


@rule("SYNC001", "blocking device syncs in hot-path functions outside the sanctioned allowlist")
def sync001(index: ProjectIndex) -> List[Finding]:
    allowlist_path = index.config.abspath(index.config.sync_allowlist_path)
    cfg = load_sync_config(allowlist_path)
    hot_paths: Dict[str, List[str]] = cfg.get("hot_paths", {})
    allowed = {
        (e["file"], e["func"], e["call"]): e
        for e in cfg.get("allowed_syncs", [])
    }

    pg = project_graph(index)
    ret_classes = pg.infer_return_classes()

    findings: List[Finding] = []
    # Allowlist entries can only shrink: every (file, func, call) must still
    # name an existing hot-path function containing that call, else the
    # entry is stale and fails the run (same semantics as a stale baseline).
    findings.extend(_validate_allowlist(index, cfg))

    for mod in index.modules:
        hot_funcs = None
        for file_key, funcs in hot_paths.items():
            if mod.relpath == file_key or mod.relpath.endswith("/" + file_key):
                hot_funcs = set(funcs)
                break
        if not hot_funcs:
            continue
        for q, fn in iter_functions(mod.tree):
            if q not in hot_funcs:
                continue

            def call_cls(call: ast.Call, _q=q, _rel=mod.relpath) -> str:
                cls = _classify_call(call)
                if cls != UNKNOWN:
                    return cls
                # Interprocedural: helper returns classified project-wide
                # (fixpoint over the v2 graph), so `rows = self._gather()`
                # taints `rows` with _gather's cross-module return class.
                callee = pg.resolve_call(_rel, _q, dotted(call.func))
                if callee is not None:
                    return ret_classes.get(callee, UNKNOWN)
                return UNKNOWN

            taint = _taint_function(fn, call_cls)

            def emit(line: int, call_name: str, detail: str) -> None:
                if (mod.relpath, q, call_name) in allowed:
                    return
                if mod.suppressed("SYNC001", line):
                    return
                findings.append(Finding(
                    "SYNC001", mod.relpath, line, q,
                    f"blocking sync {call_name}({detail}) in hot path — the decode "
                    f"step budget is 1 sync (sync_allowlist.json names it); "
                    f"allowlist with a reason or move off the step path",
                    key=f"sync:{call_name}",
                ))

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted(node.func)
                tail = name.split(".")[-1] if name else ""
                if tail in _ALWAYS_SYNC:
                    emit(node.lineno, "block_until_ready", dotted(node.func.value) if isinstance(node.func, ast.Attribute) else "")
                elif name in _DEVICE_GET:
                    emit(node.lineno, "jax.device_get", "")
                elif name in _COPYING and node.args:
                    cls = _classify_expr(node.args[0], taint, call_cls)
                    if cls in (DEVICE, UNKNOWN):
                        canon = "np.array" if tail == "array" else "np.asarray"
                        emit(node.lineno, canon, f"{ast.unparse(node.args[0])}: {cls}")
                elif name in _NARROWING and node.args:
                    if _classify_expr(node.args[0], taint, call_cls) == DEVICE:
                        emit(node.lineno, name, ast.unparse(node.args[0]))
                elif tail in _NARROWING_METHODS and isinstance(node.func, ast.Attribute):
                    if _classify_expr(node.func.value, taint, call_cls) == DEVICE:
                        emit(node.lineno, f".{tail}", ast.unparse(node.func.value))
    return findings


def _sync_call_names(fn: ast.AST) -> set:
    """Canonical sync-call names present in a function body, matching the
    vocabulary ``allowed_syncs`` entries use in their ``call`` field."""
    out = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else ""
        if tail in _ALWAYS_SYNC:
            out.add("block_until_ready")
        elif name in _DEVICE_GET:
            out.add("jax.device_get")
        elif name in _COPYING:
            out.add("np.array" if tail == "array" else "np.asarray")
        elif name in _NARROWING:
            out.add(name)
        elif tail in _NARROWING_METHODS:
            out.add(f".{tail}")
    return out


def _validate_allowlist(index: ProjectIndex, cfg: dict) -> List[Finding]:
    """Stale-entry detection for sync_allowlist.json ("can only shrink"):
    every hot_paths function must still exist, and every allowed_syncs
    entry must still name an in-scope function that contains the call."""
    rel = index.config.sync_allowlist_path.replace(os.sep, "/")
    hot_paths: Dict[str, List[str]] = cfg.get("hot_paths", {})
    findings: List[Finding] = []

    def funcs_of(file_key: str) -> Optional[Dict[str, ast.AST]]:
        for mod in index.modules:
            if mod.relpath == file_key or mod.relpath.endswith("/" + file_key):
                return dict(iter_functions(mod.tree))
        return None

    func_maps: Dict[str, Optional[Dict[str, ast.AST]]] = {}
    for file_key, names in hot_paths.items():
        func_maps[file_key] = fm = funcs_of(file_key)
        if fm is None:
            continue  # file not under the scanned paths this run — skip
        for fname in names:
            if fname not in fm:
                findings.append(Finding(
                    "SYNC001", rel, 1, "<allowlist>",
                    f"hot_paths names {file_key}:{fname} but no such function "
                    f"exists — stale scope entry, remove it",
                    key=f"stale-allowlist:hot:{file_key}:{fname}",
                ))
    for e in cfg.get("allowed_syncs", []):
        file_key, fname, call = e.get("file", ""), e.get("func", ""), e.get("call", "")
        fm = func_maps.get(file_key)
        if fm is None and file_key not in func_maps:
            func_maps[file_key] = fm = funcs_of(file_key)
        if fm is None:
            continue
        where = f"{file_key}:{fname}"
        if fname not in hot_paths.get(file_key, []):
            findings.append(Finding(
                "SYNC001", rel, 1, "<allowlist>",
                f"allowed_syncs entry {where} ({call}) is not in SYNC001 "
                f"scope (hot_paths) — dead exemption, remove it",
                key=f"stale-allowlist:scope:{where}:{call}",
            ))
            continue
        if fname not in fm:
            findings.append(Finding(
                "SYNC001", rel, 1, "<allowlist>",
                f"allowed_syncs entry {where} ({call}) names a function that "
                f"no longer exists — stale exemption, remove it",
                key=f"stale-allowlist:func:{where}:{call}",
            ))
            continue
        if call not in _sync_call_names(fm[fname]):
            findings.append(Finding(
                "SYNC001", rel, 1, "<allowlist>",
                f"allowed_syncs entry {where} no longer matches: {fname} "
                f"contains no {call} sync — the sanctioned sync was removed, "
                f"shrink the allowlist",
                key=f"stale-allowlist:call:{where}:{call}",
            ))
    return findings
