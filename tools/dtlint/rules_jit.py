"""JIT001 / JIT002 / DON001 — jit-body purity, recompile risk, donation.

These protect the two invariants the repo's perf record hangs on:

- **0 post-warmup compiles** (PR 2's compile tracker made it observable;
  warmup() precompiles the serving key space). JIT002 catches the static
  shape-leak pattern that created mid-traffic compiles twice in this
  repo's history (scheduler width variants, wave-admission shapes).
- **Traced bodies are pure.** Host calls inside a jit/pallas body run at
  TRACE time only — a ``time.monotonic()`` or ``random.random()`` inside
  a kernel silently bakes one stale value into the executable; a
  ``print``/``logging`` call fires once per compile, not per step
  (debuggers chase ghosts). JIT001 flags them via a module-local call
  graph from every ``jax.jit``/``pallas_call`` root.
- **KV/cache buffers update in place.** A jit wrapper that rewrites a
  cache buffer without donating it doubles peak HBM for the step and
  copies the whole pool (DON001); donating an arg the caller still reads
  is a use-after-free on device (DON001's inverse).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.dtlint.callgraph import ModuleGraph, project_graph, split_gid
from tools.dtlint.core import (
    Finding, ProjectIndex, dotted, enclosing_map, qualname_at, rule,
)

_HOST_CALL_PREFIXES = (
    "time.", "random.", "_random.", "np.random.", "numpy.random.",
    "logging.", "logger.", "datetime.",
)
_HOST_CALL_EXACT = {"print", "input", "open"}

_MUTABLE_FACTORIES = {"list", "dict", "set", "deque", "defaultdict", "Counter", "OrderedDict"}

# Value-laundering helpers that turn a raw length into a bucketed rung —
# ints derived through these are compile-stable by construction (the whole
# point of the bucket-rung scheme).
_BUCKET_HELPERS = {
    "next_bucket", "width_bucket", "width_rungs", "_width_bucket",
    "_chunk_budget", "_wave_s_cap", "min", "max",
}

_KV_PARAM_HINTS = ("cache", "kv")
_KV_PARAM_EXACT = {"k", "v", "c", "cache_k", "cache_v", "blocks"}


def _mutable_globals(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(v, ast.ListComp) or isinstance(v, ast.DictComp) or isinstance(v, ast.SetComp)
            ):
                out.add(node.targets[0].id)
            elif isinstance(v, ast.Call) and dotted(v.func).split(".")[-1] in _MUTABLE_FACTORIES:
                out.add(node.targets[0].id)
    return out


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound inside a function: params, assignments, for-targets,
    withitems, comprehension targets, imports."""
    names: Set[str] = set()
    a = fn.args
    for p in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
        names.add(p.arg)
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store,)):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)) and node is not fn:
            names.add(node.name)
    return names


@rule("JIT001", "host impurity (time/random/logging/print, mutable-global reads) inside jit/pallas bodies")
def jit001(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    # Whole-program reachability (v2): a scheduler-side jax.jit(lambda:
    # model.decode(...)) pulls llama.py's decode stack into scope even
    # though llama.py itself contains no jit call.
    pg = project_graph(index)
    reach_by_mod: Dict[str, Set[str]] = {}
    for g in pg.reachable_from_jit():
        relpath, q = split_gid(g)
        reach_by_mod.setdefault(relpath, set()).add(q)
    for mod in index.modules:
        reach = reach_by_mod.get(mod.relpath, set())
        if not reach:
            continue
        graph = pg.graphs[mod.relpath]
        mut_globals = _mutable_globals(mod.tree)
        for q in sorted(reach):
            info = graph.funcs.get(q)
            if info is None:
                continue
            fn = info.node
            locals_ = _local_bindings(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = dotted(node.func)
                    hit = name in _HOST_CALL_EXACT or any(
                        name.startswith(p) for p in _HOST_CALL_PREFIXES
                    )
                    if hit and not mod.suppressed("JIT001", node.lineno):
                        findings.append(Finding(
                            "JIT001", mod.relpath, node.lineno, q,
                            f"host-impure call {name}() inside jit/pallas-reachable body "
                            f"(runs at trace time, not per step)",
                            key=f"call:{name}",
                        ))
                elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    if node.id in mut_globals and node.id not in locals_:
                        if not mod.suppressed("JIT001", node.lineno):
                            findings.append(Finding(
                                "JIT001", mod.relpath, node.lineno, q,
                                f"read of mutable module global '{node.id}' inside "
                                f"jit/pallas-reachable body (value frozen at trace time)",
                                key=f"global:{node.id}",
                            ))
    return findings


def _shape_scalars(fn: ast.AST) -> Set[str]:
    """Names holding raw Python ints derived from len()/shape — passing one
    straight into a jitted callable keys a fresh executable per value."""
    tainted: Set[str] = set()

    def is_shapey(expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):
            name = dotted(expr.func)
            if name == "len":
                return True
            if name.split(".")[-1] in _BUCKET_HELPERS:
                return False  # laundered through a bucket rung
            return False
        if isinstance(expr, ast.Subscript) and isinstance(expr.value, ast.Attribute):
            if expr.value.attr == "shape":
                return True
        if isinstance(expr, ast.BinOp):
            return is_shapey(expr.left) or is_shapey(expr.right)
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        return False

    # Two passes so x = len(a); y = x + 1 taints y.
    for _ in range(2):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                if is_shapey(node.value):
                    tainted.add(node.targets[0].id)
                elif node.targets[0].id in tainted:
                    # reassigned to something clean (e.g. a bucket helper)
                    tainted.discard(node.targets[0].id)
    return tainted


def _is_jitted_callee(name: str, bound: Dict[str, "object"]) -> bool:
    if name in bound:
        return True
    tail = name.split(".")[-1]
    return tail.endswith("_jit")


@rule("JIT002", "recompile risk: raw shape scalars into jitted calls; unstable static_argnums/argnames")
def jit002(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.modules:
        graph = ModuleGraph(mod)
        bound = graph.bound_wrappers()

        # (a) static_argnums/static_argnames pointing at hashable-unstable
        # params (mutable defaults / container annotations).
        for w in graph.wrappers:
            if w.target is None or (not w.static_argnums and not w.static_argnames):
                continue
            info = graph.funcs.get(w.target)
            if info is None:
                continue
            fn = info.node
            params = [p.arg for p in fn.args.posonlyargs + fn.args.args]
            defaults = fn.args.defaults
            default_by_param = {}
            if defaults:
                for p, d in zip(params[-len(defaults):], defaults):
                    default_by_param[p] = d
            static_params = set(w.static_argnames)
            for i in w.static_argnums:
                if 0 <= i < len(params):
                    static_params.add(params[i])
            for pname in sorted(static_params):
                ann = None
                for p in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
                    if p.arg == pname:
                        ann = p.annotation
                d = default_by_param.get(pname)
                unstable = isinstance(d, (ast.List, ast.Dict, ast.Set))
                if ann is not None:
                    aname = dotted(ann) or (
                        dotted(ann.value) if isinstance(ann, ast.Subscript) else ""
                    )
                    if aname.split(".")[-1].lower() in ("list", "dict", "set"):
                        unstable = True
                if unstable and not mod.suppressed("JIT002", w.line):
                    findings.append(Finding(
                        "JIT002", mod.relpath, w.line, w.target,
                        f"static arg '{pname}' of jitted {w.target} is hashable-unstable "
                        f"(list/dict/set) — every call retraces or TypeErrors",
                        key=f"static:{pname}",
                    ))

        # (b) call sites handing raw shape-derived Python scalars (or bare
        # len()) to a jitted callable — each distinct value compiles a new
        # executable; route through the bucket-rung helpers instead.
        line_map = enclosing_map(mod.tree)
        for q, info in graph.funcs.items():
            fn = info.node
            tainted = _shape_scalars(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted(node.func)
                if not callee or not _is_jitted_callee(callee, bound):
                    continue
                # A raw Python scalar only keys a fresh executable in a
                # STATIC position (traced positions key on shape+dtype).
                # With the wrapper resolved, restrict to its static
                # argnums; unresolved `*_jit` callees stay conservative.
                w = bound.get(callee)
                static_idx = set(w.static_argnums) if w is not None else None
                for i, arg in enumerate(node.args):
                    if static_idx is not None and i not in static_idx:
                        continue
                    bad = None
                    if isinstance(arg, ast.Call) and dotted(arg.func) == "len":
                        bad = "len(...)"
                    elif isinstance(arg, ast.Name) and arg.id in tainted:
                        bad = arg.id
                    if bad and not mod.suppressed("JIT002", node.lineno):
                        findings.append(Finding(
                            "JIT002", mod.relpath, node.lineno,
                            qualname_at(line_map, node.lineno),
                            f"raw shape scalar {bad!r} passed to jitted {callee}() — "
                            f"compiles one executable per distinct value; bucket it "
                            f"(next_bucket/width_bucket) or pass jnp.int32(...)",
                            key=f"shape:{bad}",
                        ))
    return findings


def _kv_param(name: str) -> bool:
    low = name.lower()
    return name in _KV_PARAM_EXACT or any(h in low for h in _KV_PARAM_HINTS)


@rule("DON001", "KV/cache-writing jit wrappers without donate_argnums; donated args reused by the caller")
def don001(index: ProjectIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.modules:
        graph = ModuleGraph(mod)
        line_map = enclosing_map(mod.tree)

        # (a) wrapper writes a KV/cache param but doesn't donate it.
        for w in graph.wrappers:
            if w.target is None or w.kind != "jit":
                continue
            info = graph.funcs.get(w.target)
            if info is None:
                continue
            fn = info.node
            params = [p.arg for p in fn.args.posonlyargs + fn.args.args]
            donated = set(w.donate_argnames)
            for i in w.donate_argnums:
                if 0 <= i < len(params):
                    donated.add(params[i])
            for idx, pname in enumerate(params):
                if not _kv_param(pname) or pname in donated:
                    continue
                # `p.at[...]` and pytree-field writes `p.q.at[...]` both
                # count: the buffer being functionally updated is (part of)
                # the parameter.
                writes = any(
                    isinstance(n, ast.Attribute) and n.attr == "at"
                    and dotted(n.value).split(".")[0] == pname
                    for n in ast.walk(fn)
                )
                if writes and not mod.suppressed("DON001", w.line):
                    findings.append(Finding(
                        "DON001", mod.relpath, w.line, w.target,
                        f"jitted {w.target} writes cache param '{pname}' "
                        f"(.at[...] update) without donate_argnums — the step "
                        f"double-buffers the whole pool in HBM",
                        key=f"nodonate:{pname}",
                    ))

        # (b) caller reuses an arg it donated (device use-after-free).
        donating = {
            w.bound_name: w for w in graph.wrappers
            if w.bound_name and w.donate_argnums
        }
        if not donating:
            continue
        for q, info in graph.funcs.items():
            fn = info.node
            # Line spans of every jit-wrapper call in this function: a load
            # that is itself an argument to a (re-)dispatch is the normal
            # donate→reassign step pattern (and mutually exclusive branches
            # each carry their own dispatch), not a stale read.
            jit_call_spans = []
            for n in ast.walk(fn):
                if isinstance(n, ast.Call):
                    cname = dotted(n.func)
                    if cname and (cname in donating or cname.split(".")[-1].endswith("_jit")):
                        jit_call_spans.append((n.lineno, getattr(n, "end_lineno", n.lineno)))

            def in_jit_call(line: int) -> bool:
                return any(lo <= line <= hi for lo, hi in jit_call_spans)

            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                w = donating.get(dotted(node.func))
                if w is None:
                    continue
                call_end = getattr(node, "end_lineno", node.lineno)
                for i in w.donate_argnums:
                    if i >= len(node.args):
                        continue
                    arg_name = dotted(node.args[i])
                    if not arg_name:
                        continue
                    first_store = None
                    first_load = None
                    for n in ast.walk(fn):
                        nm = dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) else ""
                        if nm != arg_name:
                            continue
                        ctx = getattr(n, "ctx", None)
                        if isinstance(ctx, ast.Store) and n.lineno >= node.lineno:
                            if first_store is None or n.lineno < first_store:
                                first_store = n.lineno
                        elif isinstance(ctx, ast.Load) and n.lineno > call_end and not in_jit_call(n.lineno):
                            if first_load is None or n.lineno < first_load:
                                first_load = n.lineno
                    if first_load is not None and (first_store is None or first_load < first_store):
                        if not mod.suppressed("DON001", first_load):
                            findings.append(Finding(
                                "DON001", mod.relpath, first_load,
                                qualname_at(line_map, first_load),
                                f"'{arg_name}' is read after being donated to "
                                f"{dotted(node.func)}() at line {node.lineno} — "
                                f"donated buffers are invalid after the call",
                                key=f"reuse:{arg_name}",
                            ))
    return findings
