"""WIRE001 — cross-process request-wire drift.

The frontend process (preprocessor/router) and the worker process
(engine/mocker) agree on the request wire only by convention: a plain dict
whose keys are string literals on both sides, with no shared schema object
crossing the process boundary (``PreprocessedRequest.to_wire`` is the
closest thing to one, but half the traffic mutates the dict after it).
Nothing catches a renamed or dropped key until a request silently loses its
sampling params in production. This rule diffs the two sides statically:

- **channel A (top-level request keys)**: every key a configured *reader*
  consumes must be produced by some configured *writer* (a **ghost read**
  returns the reader's ``.get`` default forever), and every key a writer
  produces must be consumed by some reader (a **dead write** is either dead
  code or a misspelled key whose real reader is starving).
- **channel B (stop_conditions sub-keys)**: same two-directional check for
  the nested ``stop_conditions`` dict, whose writer
  (``stop_conditions_from_request``) and reader (``StopConditions.from_dict``)
  live three hops apart. Chained reads like
  ``(req.get("stop_conditions") or {}).get("stop")`` and mutations through
  ``stop``-named locals are routed here, not to channel A.
- **channel C (mocker stats parity)**: every stats family the mocker's
  emitters publish must exist on the real engine plane (literal match or an
  engine f-string wildcard) — the planner/observer tunes against the mocker,
  so a mocker-only family calibrates against a metric production never has.

Scopes are *function*-qualified (``path::qualname``), not file-level:
receiver names collide across protocol layers — the preprocessor's
``request`` parameter is the OpenAI body in ``transform_request`` but the
wire dict in ``transform_response`` — so only per-function roles keep the
OpenAI-body keys out of the wire universe. Within a configured function,
only request-shaped receivers count (the dict-typed first parameter,
``*req*``/``wire`` names, and — for writers — locals that are returned),
which keeps sub-dicts like ``sampling_options`` lookups out of channel A.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.dtlint.core import (
    Finding, ProjectIndex, dotted, iter_functions, rule,
)
from tools.dtlint.rules_metrics import _dataclass_fields, _fstring_pattern

# (channel, key) -> [(file, line, qualname)]
Sites = Dict[Tuple[str, str], List[Tuple[str, int, str]]]

CH_TOP = "request"
CH_STOP = "stop_conditions"


def _match_scope(relpath: str, entries: Tuple[str, ...]) -> List[str]:
    """Qualnames configured for this file (entries are 'path::qualname')."""
    out = []
    for e in entries:
        path, _, q = e.partition("::")
        if relpath == path or relpath.endswith("/" + path):
            out.append(q)
    return out


def _functions_for(index: ProjectIndex, entries: Tuple[str, ...]):
    """Yield (mod, qualname, fn_node, stop_tagged=False) for configured
    functions. Walking the node covers nested defs (transform_response's
    inner ``gen()`` reads count for the outer entry)."""
    for mod in index.modules:
        quals = _match_scope(mod.relpath, entries)
        if not quals:
            continue
        for q, fn in iter_functions(mod.tree):
            if q in quals:
                yield mod, q, fn


def _first_param(fn: ast.AST) -> Optional[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return None
    names = [a.arg for a in args.args if a.arg not in ("self", "cls")]
    return names[0] if names else None


def _route(recv: ast.AST, allow: Set[str]) -> Optional[str]:
    """Which channel a receiver belongs to: CH_STOP for stop-named locals
    and ``(... .get("stop_conditions") ...)`` chains, CH_TOP for
    request-shaped names, None (ignored) otherwise."""
    name = dotted(recv)
    if name:
        tail = name.split(".")[-1]
        if "stop" in tail:
            return CH_STOP
        if "req" in tail or tail in ("wire", "frame") or tail in allow:
            return CH_TOP
        return None
    try:
        src = ast.unparse(recv)
    except Exception:  # pragma: no cover - malformed receiver
        return None
    return CH_STOP if '"stop_conditions"' in src or "'stop_conditions'" in src else None


def _const_key(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _returned_names(fn: ast.AST) -> Set[str]:
    """Locals that leave the function as a wire payload: returned or
    yielded (engine output frames are yielded dicts, not returned ones)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
        elif isinstance(node, ast.Yield) and isinstance(node.value, ast.Name):
            out.add(node.value.id)
    return out


def _note(sites: Sites, ch: str, key: str, mod, line: int, q: str) -> None:
    sites.setdefault((ch, key), []).append((mod.relpath, line, q))


def _collect_writes(index: ProjectIndex, sites: Sites) -> None:
    cfg = index.config
    for mod, q, fn in _functions_for(index, cfg.wire_writers):
        allow = _returned_names(fn)
        p = _first_param(fn)
        if p:
            allow.add(p)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Subscript) and _const_key(tgt.slice)):
                        continue
                    key = _const_key(tgt.slice)
                    ch = _route(tgt.value, allow)
                    if ch is None:
                        continue
                    _note(sites, ch, key, mod, tgt.lineno, q)
                    # A dict literal stored under "stop_conditions" writes
                    # its own keys onto channel B (disagg's max_tokens=1).
                    if ch == CH_TOP and key == CH_STOP and isinstance(node.value, ast.Dict):
                        for k in node.value.keys:
                            sub = _const_key(k) if k is not None else None
                            if sub:
                                _note(sites, CH_STOP, sub, mod, k.lineno, q)
            elif isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    key = _const_key(k) if k is not None else None
                    if key:
                        _note(sites, CH_TOP, key, mod, k.lineno, q)
        # Dict literals assigned to a wire-shaped local: ``d = {...};
        # return d`` (to_wire) or ``frame = {...}`` later yielded/queued —
        # the literal's top-level keys are wire writes.
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and _route(node.targets[0], allow) == CH_TOP
                    and isinstance(node.value, ast.Dict)):
                for k in node.value.keys:
                    key = _const_key(k) if k is not None else None
                    if key:
                        _note(sites, CH_TOP, key, mod, k.lineno, q)
    # Stop-channel writers: every literal dict they return is the
    # stop_conditions payload itself.
    for mod, q, fn in _functions_for(index, cfg.wire_stop_writers):
        ret = _returned_names(fn)
        for node in ast.walk(fn):
            d = None
            if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
                d = node.value
            elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id in ret
                    and isinstance(node.value, ast.Dict)):
                d = node.value
            elif (isinstance(node, ast.Assign) and isinstance(node.targets[0], ast.Subscript)
                    and _const_key(node.targets[0].slice)):
                _note(sites, CH_STOP, _const_key(node.targets[0].slice), mod,
                      node.lineno, q)
            if d is not None:
                for k in d.keys:
                    key = _const_key(k) if k is not None else None
                    if key:
                        _note(sites, CH_STOP, key, mod, k.lineno, q)


def _collect_reads(index: ProjectIndex, sites: Sites) -> None:
    cfg = index.config

    def scan(mod, q, fn, force_stop: bool) -> None:
        allow: Set[str] = set()
        p = _first_param(fn)
        if p:
            allow.add(p)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "pop") and node.args):
                key = _const_key(node.args[0])
                if key is None:
                    continue
                ch = CH_STOP if force_stop else _route(node.func.value, allow)
                if ch is not None:
                    _note(sites, ch, key, mod, node.lineno, q)
            elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                key = _const_key(node.slice)
                if key is None:
                    continue
                ch = CH_STOP if force_stop else _route(node.value, allow)
                if ch is not None:
                    _note(sites, ch, key, mod, node.lineno, q)

    for mod, q, fn in _functions_for(index, cfg.wire_readers):
        scan(mod, q, fn, force_stop=False)
    for mod, q, fn in _functions_for(index, cfg.wire_stop_readers):
        scan(mod, q, fn, force_stop=True)


def _stats_keys_for(mod, cfg) -> Tuple[Set[str], List[str]]:
    """(literal stats families, f-string wildcard patterns) a module's
    emitter functions publish — the per-module slice of MET001's
    collect_wire_keys, for engine/mocker parity."""
    literals: Set[str] = set()
    wildcards: List[str] = []
    for q, fn in iter_functions(mod.tree):
        if q.split(".")[-1] not in cfg.met001_emitters:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if k is None:
                        continue
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        literals.add(k.value)
                    elif isinstance(k, ast.JoinedStr):
                        wildcards.append(_fstring_pattern(k))
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Subscript):
                        key = _const_key(tgt.slice)
                        if key:
                            literals.add(key)
                        elif isinstance(tgt.slice, ast.JoinedStr):
                            wildcards.append(_fstring_pattern(tgt.slice))
            elif isinstance(node, ast.Call) and "self.__dict__" in ast.unparse(node):
                if "." in q:
                    cls = q.rsplit(".", 2)[-2]
                    literals.update(n for n, _ in _dataclass_fields(mod.tree, cls))
    return literals, wildcards


def _mocker_parity(index: ProjectIndex) -> List[Finding]:
    cfg = index.config
    engine_lits: Set[str] = set()
    engine_wild: List[str] = []
    mocker_mod = None
    for mod in index.modules:
        if mod.relpath == cfg.mocker_path or mod.relpath.endswith("/" + cfg.mocker_path):
            mocker_mod = mod
            continue
        if any(x in mod.relpath for x in cfg.met001_exclude):
            continue
        lits, wild = _stats_keys_for(mod, cfg)
        engine_lits |= lits
        engine_wild.extend(wild)
    # The aggregator's declared key lists ARE the engine plane's contract —
    # a mocker family the aggregator already fleet-sums is real parity even
    # if no engine emitter spells it as a literal in an emitter function.
    agg = index.module(cfg.aggregator_path)
    if agg is not None:
        from tools.dtlint.rules_metrics import _key_list_lines

        for lname in ("COUNTER_KEYS", "GAUGE_KEYS", "DIGEST_KEYS"):
            engine_lits |= set(_key_list_lines(agg.tree, lname))
    if mocker_mod is None:
        return []
    patterns = [re.compile(w) for w in engine_wild]
    findings: List[Finding] = []
    lits, _ = _stats_keys_for(mocker_mod, cfg)
    # Re-walk for line attribution (sets lose it).
    for q, fn in iter_functions(mocker_mod.tree):
        if q.split(".")[-1] not in cfg.met001_emitters:
            continue
        for node in ast.walk(fn):
            keys: List[Tuple[str, int]] = []
            if isinstance(node, ast.Dict):
                keys = [(k.value, k.lineno) for k in node.keys
                        if isinstance(k, ast.Constant) and isinstance(k.value, str)]
            elif isinstance(node, ast.Assign):
                keys = [(_const_key(t.slice), t.lineno) for t in node.targets
                        if isinstance(t, ast.Subscript) and _const_key(t.slice)]
            for key, line in keys:
                if key in engine_lits or any(p.fullmatch(key) for p in patterns):
                    continue
                if mocker_mod.suppressed("WIRE001", line):
                    continue
                findings.append(Finding(
                    "WIRE001", mocker_mod.relpath, line, q,
                    f"mocker stats family '{key}' has no counterpart on the "
                    f"real engine plane — planner calibration against the "
                    f"mocker would tune on a metric production never emits",
                    key=f"mocker-stats:{key}",
                ))
    return findings


@rule("WIRE001", "cross-process wire drift: ghost reads, dead writes, stop_conditions sub-key drift, mocker stats parity")
def wire001(index: ProjectIndex) -> List[Finding]:
    writes: Sites = {}
    reads: Sites = {}
    _collect_writes(index, writes)
    _collect_reads(index, reads)

    findings: List[Finding] = []
    written = {k for k in writes}
    read = {k for k in reads}

    for (ch, key), sites in sorted(reads.items()):
        if (ch, key) in written:
            continue
        relpath, line, q = sites[0]
        mod = index.module(relpath)
        if mod is not None and mod.suppressed("WIRE001", line):
            continue
        where = "request" if ch == CH_TOP else "stop_conditions"
        findings.append(Finding(
            "WIRE001", relpath, line, q,
            f"ghost read: {where} key '{key}' is read here but no configured "
            f"wire writer ever produces it — the .get() default is the only "
            f"value this branch will ever see",
            key=f"ghost-read:{ch}:{key}",
        ))
    for (ch, key), sites in sorted(writes.items()):
        if (ch, key) in read:
            continue
        relpath, line, q = sites[0]
        mod = index.module(relpath)
        if mod is not None and mod.suppressed("WIRE001", line):
            continue
        where = "request" if ch == CH_TOP else "stop_conditions"
        findings.append(Finding(
            "WIRE001", relpath, line, q,
            f"dead write: {where} key '{key}' is written here but no "
            f"configured wire reader ever consumes it — dead code, or a "
            f"misspelling whose real reader is starving",
            key=f"dead-write:{ch}:{key}",
        ))

    findings.extend(_mocker_parity(index))
    return findings


def wire_universe(index: ProjectIndex) -> Dict[str, Dict[str, List[Tuple[str, int, str]]]]:
    """Debug/test export: the extracted wire key universe per channel."""
    writes: Sites = {}
    reads: Sites = {}
    _collect_writes(index, writes)
    _collect_reads(index, reads)
    out: Dict[str, Dict[str, List[Tuple[str, int, str]]]] = {
        "writes": {}, "reads": {},
    }
    for (ch, key), sites in writes.items():
        out["writes"][f"{ch}:{key}"] = sites
    for (ch, key), sites in reads.items():
        out["reads"][f"{ch}:{key}"] = sites
    return out
