"""THR001 — fields written from ≥2 thread entry points without a lock or
``# guarded-by:`` annotation.

The serving plane is deliberately multi-threaded: the scheduler steps on a
worker thread, the tracer exports on a writer thread, the stall watchdog
probes from the poll cadence, and stats handlers read (and occasionally
reset) state from the event loop. Plain-int last-write-wins races are an
explicit, documented choice in some of these (flight_recorder's module
docstring) — but that choice must be *visible at the write site*, not
tribal knowledge, or the next PR adds a read-modify-write and loses
increments silently.

Mechanics, per class:

- **Entry points** = methods passed as ``threading.Thread(target=...)``
  within the class, plus the (file, qualname) pairs designated in
  ``LintConfig.thread_entries``. Each entry's intra-class call closure is
  one *domain*; everything else (minus ``__init__``) is the "main" domain.
- An attribute assigned (``self.x = ...`` / ``self.x += ...``) in ≥2
  domains is flagged unless every cross-domain write is under a
  ``with self.<...lock...>:`` block, or the write line (or the attribute's
  ``__init__`` line) carries a ``# guarded-by: <lock>`` annotation.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.dtlint.core import Finding, ProjectIndex, dotted, rule

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}


def _class_methods(cls: ast.ClassDef) -> Dict[str, ast.AST]:
    return {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def _thread_targets(cls: ast.ClassDef) -> Set[str]:
    """Method names passed as Thread(target=self.X) anywhere in the class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Call) and dotted(node.func).endswith("Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    name = dotted(kw.value)
                    if name.startswith("self."):
                        out.add(name[len("self."):])
    return out


def _closure(methods: Dict[str, ast.AST], start: str) -> Set[str]:
    seen: Set[str] = set()
    stack = [start]
    while stack:
        m = stack.pop()
        if m in seen or m not in methods:
            continue
        seen.add(m)
        for node in ast.walk(methods[m]):
            if isinstance(node, ast.Call):
                name = dotted(node.func)
                if name.startswith("self."):
                    stack.append(name[len("self."):])
    return seen


def _locked_lines(fn: ast.AST) -> Set[int]:
    """Lines covered by a ``with self.<something lock-ish>:`` block."""
    out: Set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = dotted(item.context_expr)
                if name.startswith("self.") and "lock" in name.lower():
                    end = getattr(node, "end_lineno", node.lineno)
                    out.update(range(node.lineno, end + 1))
    return out


def _attr_writes(fn: ast.AST) -> List[Tuple[str, int]]:
    """[(attr, line)] for every self.<attr> store in the function."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(fn):
        tgt: Optional[ast.AST] = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    tgt = t
                    if isinstance(t.value, ast.Name) and t.value.id == "self":
                        out.append((t.attr, t.lineno))
        elif isinstance(node, ast.AugAssign) and isinstance(node.target, ast.Attribute):
            t = node.target
            if isinstance(t.value, ast.Name) and t.value.id == "self":
                out.append((t.attr, t.lineno))
    return out


@rule("THR001", "fields written from ≥2 thread entry points without a lock or guarded-by annotation")
def thr001(index: ProjectIndex) -> List[Finding]:
    cfg = index.config
    findings: List[Finding] = []
    for mod in index.modules:
        designated = {
            q for f, q in cfg.thread_entries
            if mod.relpath == f or mod.relpath.endswith("/" + f)
        }
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = _class_methods(cls)
            entries = _thread_targets(cls)
            for q in designated:
                c, _, m = q.rpartition(".")
                if c == cls.name and m in methods:
                    entries.add(m)
            if not entries:
                continue

            domains: Dict[str, Set[str]] = {
                e: _closure(methods, e) for e in sorted(entries)
            }
            # Main domain = closure of every method NOT already inside an
            # entry closure. A shared helper (e.g. a drain called from both
            # the scrape path and the step path) must count for BOTH
            # domains — that cross-thread shared write is exactly the bug
            # class this rule exists for.
            entry_members = set().union(*domains.values()) if domains else set()
            main_roots = {
                m for m in methods
                if m not in entry_members and m not in _INIT_METHODS
            }
            main: Set[str] = set()
            for m in main_roots:
                main |= _closure(methods, m)
            main -= _INIT_METHODS
            if main:
                domains["<main>"] = main

            # attr -> {domain: [(line, locked, annotated)]}
            writes: Dict[str, Dict[str, List[Tuple[int, bool, bool]]]] = {}
            init_annotated: Set[str] = set()
            for m in _INIT_METHODS & set(methods):
                for attr, line in _attr_writes(methods[m]):
                    if "guarded-by:" in mod.line_text(line):
                        init_annotated.add(attr)
            for dom, members in domains.items():
                for m in members:
                    if m in _INIT_METHODS:
                        continue
                    fn = methods.get(m)
                    if fn is None:
                        continue
                    locked = _locked_lines(fn)
                    for attr, line in _attr_writes(fn):
                        ann = "guarded-by:" in mod.line_text(line)
                        writes.setdefault(attr, {}).setdefault(dom, []).append(
                            (line, line in locked, ann)
                        )

            for attr, per_dom in sorted(writes.items()):
                if len(per_dom) < 2 or attr in init_annotated:
                    continue
                unguarded = [
                    (dom, line)
                    for dom, sites in per_dom.items()
                    for line, locked, ann in sites
                    if not locked and not ann
                ]
                if len({dom for dom, _ in unguarded}) < 2:
                    continue  # at most one domain writes without protection
                # Report at the first unguarded non-main write (the thread
                # side is where the annotation belongs).
                dom, line = min(
                    unguarded, key=lambda x: (x[0] == "<main>", x[1])
                )
                if mod.suppressed("THR001", line):
                    continue
                findings.append(Finding(
                    "THR001", mod.relpath, line, f"{cls.name}.{attr}",
                    f"'{attr}' is written from {len(per_dom)} thread domains "
                    f"({', '.join(sorted(per_dom))}) without a lock — hold a "
                    f"threading.Lock or annotate the write '# guarded-by: "
                    f"<lock or single-writer argument>'",
                    key=f"field:{attr}",
                ))
    return findings
