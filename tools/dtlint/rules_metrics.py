"""MET001 — cross-file metrics drift (code ↔ aggregator key lists ↔ Grafana).

A counter the scheduler increments is worthless if the aggregator drops it
at the scrape (not in ``COUNTER_KEYS``) or no dashboard panel pins it —
and a pinned panel over a key nobody emits rots into permanent "no data"
(the dashboard failure mode PR 2 fixed once already). The dynamic half of
``test_metrics_hygiene.py`` proves keys *render*; this rule closes the
static triangle over the whole tree:

  (a) every counter key emitted on the worker-scrape wire (``to_wire``/
      ``to_stats``/``stats_handler``/``kv_gauges``/``stats`` dict keys
      ending ``_total``) is registered in ``COUNTER_KEYS``;
  (b) every registered COUNTER/GAUGE key is emitted somewhere (f-string
      keys like ``step_{phase}_steps_total`` match as wildcards);
  (c) every registered key is pinned by at least one Grafana panel expr;
  (d) every ``dynamo_component_worker_*`` family a panel references is a
      registered key.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Dict, List, Set, Tuple

from tools.dtlint.core import Finding, ProjectIndex, iter_functions, rule


def _key_list_lines(tree: ast.Module, list_name: str) -> Dict[str, int]:
    """{key: lineno} for the elements of a module-level tuple constant."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == list_name
            and isinstance(node.value, (ast.Tuple, ast.List))
        ):
            out = {}
            for el in node.value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out[el.value] = el.lineno
            return out
    return {}


def _fstring_pattern(node: ast.JoinedStr) -> str:
    """Regex for an f-string key: literal parts verbatim, each formatted
    value becomes ``\\w+``."""
    parts = []
    for v in node.values:
        if isinstance(v, ast.Constant):
            parts.append(re.escape(str(v.value)))
        else:
            parts.append(r"\w+")
    return "^" + "".join(parts) + "$"


def _dataclass_fields(tree: ast.Module, cls_name: str) -> List[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls_name:
            out = []
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    out.append((stmt.target.id, stmt.lineno))
            return out
    return []


def collect_wire_keys(index: ProjectIndex):
    """(literal_keys {key: (file, line)}, wildcard_patterns [(regex, file, line)])
    from every emitter function in the scanned tree."""
    cfg = index.config
    literals: Dict[str, Tuple[str, int]] = {}
    wildcards: List[Tuple[str, str, int]] = []
    for mod in index.modules:
        if any(x in mod.relpath for x in cfg.met001_exclude):
            continue
        for q, fn in iter_functions(mod.tree):
            if q.split(".")[-1] not in cfg.met001_emitters:
                continue

            def note_key(knode: ast.AST) -> None:
                if isinstance(knode, ast.Constant) and isinstance(knode.value, str):
                    literals.setdefault(knode.value, (mod.relpath, knode.lineno))
                elif isinstance(knode, ast.JoinedStr):
                    wildcards.append((_fstring_pattern(knode), mod.relpath, knode.lineno))

            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        if k is not None:
                            note_key(k)
                elif isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Subscript):
                            note_key(tgt.slice)
                elif isinstance(node, ast.Call):
                    # self.__dict__.copy() in to_wire ⇒ the dataclass's own
                    # fields are the wire keys (ForwardPassMetrics pattern).
                    src = ast.unparse(node)
                    if "self.__dict__" in src and "." in q:
                        cls = q.rsplit(".", 2)[-2]
                        for fname, fline in _dataclass_fields(mod.tree, cls):
                            literals.setdefault(fname, (mod.relpath, fline))
    return literals, wildcards


def _grafana_worker_keys(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        dash = json.load(f)
    exprs: List[str] = []

    def walk(o):
        if isinstance(o, dict):
            if isinstance(o.get("expr"), str):
                exprs.append(o["expr"])
            for v in o.values():
                walk(v)
        elif isinstance(o, list):
            for v in o:
                walk(v)

    walk(dash)
    keys: Set[str] = set()
    for e in exprs:
        for m in re.findall(r"dynamo_component_worker_([a-zA-Z0-9_]+)", e):
            keys.add(re.sub(r"_(bucket|sum|count)$", "", m))
    return keys


@rule("MET001", "metrics drift: wire keys ↔ aggregator COUNTER_KEYS/GAUGE_KEYS ↔ Grafana panel exprs")
def met001(index: ProjectIndex) -> List[Finding]:
    cfg = index.config
    agg = index.module(cfg.aggregator_path)
    if agg is None:
        try:
            from tools.dtlint.core import SourceModule

            agg = SourceModule(cfg.root, cfg.aggregator_path)
        except OSError:
            return []
    counter_lines = _key_list_lines(agg.tree, "COUNTER_KEYS")
    gauge_lines = _key_list_lines(agg.tree, "GAUGE_KEYS")
    counters = set(counter_lines)
    gauges = set(gauge_lines)
    registered = counters | gauges

    literals, wildcards = collect_wire_keys(index)
    wc_res = [(re.compile(p), f, ln) for p, f, ln in wildcards]

    def emitted(key: str) -> bool:
        if key in literals:
            return True
        return any(r.match(key) for r, _, _ in wc_res)

    grafana_path = cfg.abspath(cfg.grafana_path)
    pinned = _grafana_worker_keys(grafana_path)
    grafana_rel = cfg.grafana_path.replace(os.sep, "/")

    findings: List[Finding] = []

    # (a) counters on the wire but not registered.
    for key, (file, line) in sorted(literals.items()):
        if not key.endswith("_total") or key in registered:
            continue
        mod = index.module(file)
        if mod is not None and mod.suppressed("MET001", line):
            continue
        findings.append(Finding(
            "MET001", file, line, "<wire>",
            f"counter '{key}' is emitted on the worker-scrape wire but not "
            f"registered in metrics_aggregator COUNTER_KEYS — the aggregator "
            f"drops it at every scrape",
            key=f"unregistered:{key}",
        ))

    agg_rel = agg.relpath
    for key in sorted(registered):
        line = counter_lines.get(key) or gauge_lines.get(key) or 1
        if agg.suppressed("MET001", line):
            continue
        # (b) registered but nothing emits it.
        if not emitted(key):
            findings.append(Finding(
                "MET001", agg_rel, line, "<keys>",
                f"'{key}' is registered in the aggregator key lists but no "
                f"to_wire/to_stats/stats_handler emits it — dead key or "
                f"renamed emitter",
                key=f"unemitted:{key}",
            ))
        # (c) registered but no Grafana panel pins it.
        if pinned and key not in pinned:
            findings.append(Finding(
                "MET001", agg_rel, line, "<keys>",
                f"'{key}' is registered but no Grafana panel expr references "
                f"dynamo_component_worker_{key} — unpinned metrics rot",
                key=f"unpinned:{key}",
            ))

    # (d) dashboard references an unknown worker key.
    for key in sorted(pinned):
        base = key[:-len("_total")] if key.endswith("_total") else key
        if key in registered or base in registered:
            continue
        findings.append(Finding(
            "MET001", grafana_rel, 1, "<grafana>",
            f"dashboard references dynamo_component_worker_{key} but '{key}' "
            f"is in neither COUNTER_KEYS nor GAUGE_KEYS — the panel can never "
            f"show data",
            key=f"unknown:{key}",
        ))
    return findings
