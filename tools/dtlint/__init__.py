"""dtlint: static invariant checker for this repo's jit hygiene, sync
points, donation, metrics plumbing, and thread safety.

Usage: ``python -m tools.dtlint [--rule R] [--baseline f.json] [--json]``.
See ``tools/dtlint/README.md`` for the rule catalogue.
"""

from tools.dtlint.core import (  # noqa: F401
    Finding,
    LintConfig,
    LintResult,
    ProjectIndex,
    RULE_DOCS,
    RULES,
    apply_baseline,
    load_baseline,
    run_lint,
)
