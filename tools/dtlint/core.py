"""dtlint core: source index, findings, suppressions, baseline.

The repo's performance invariants (0 post-warmup compiles, 1 blocking sync
per decode step, every counter registered + pinned) are dynamic properties
enforced by a handful of tests that exercise specific paths. dtlint turns
them into *static* properties of the whole tree: every rule is a pure
``ast`` pass (no new deps, no JAX import, runs in seconds on CPU-less CI).

Vocabulary:

- A **Finding** is one violation: (rule, file, line, qualname, message,
  key). ``key`` is a short stable token (usually the offending call or
  metric name) so baseline entries survive line-number drift.
- A **suppression** is an inline ``# dtlint: disable=RULE[,RULE]`` comment
  on the flagged line, or a file-wide ``# dtlint: disable-file=RULE`` in
  the first 10 lines. Suppressions are for code where the rule is wrong;
  deliberate *exceptions to the invariant* belong in the baseline with a
  reason.
- The **baseline** (``dtlint_baseline.json``) lists reviewed, deliberate
  findings. Every entry must carry a ``reason`` string and must still
  match a live finding — stale entries are themselves an error, so the
  baseline can only shrink or be consciously re-reviewed.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple

_SUPPRESS_RE = re.compile(r"#\s*dtlint:\s*disable=([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*dtlint:\s*disable-file=([A-Z]+\d+(?:\s*,\s*[A-Z]+\d+)*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    file: str          # repo-relative, forward slashes
    line: int
    qualname: str      # enclosing Class.func (or "<module>")
    message: str
    key: str           # stable short token for baseline matching

    def ident(self) -> Tuple[str, str, str, str]:
        """Baseline identity: line numbers drift, these don't."""
        return (self.rule, self.file, self.qualname, self.key)

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "file": self.file, "line": self.line,
            "qualname": self.qualname, "key": self.key, "message": self.message,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: {self.rule} [{self.qualname}] {self.message}"


class SourceModule:
    """One parsed Python file plus its suppression table."""

    def __init__(self, root: str, relpath: str) -> None:
        self.relpath = relpath.replace(os.sep, "/")
        self.path = os.path.join(root, relpath)
        with open(self.path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.relpath)
        self._line_suppress: Dict[int, set] = {}
        self._file_suppress: set = set()
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self._line_suppress[i] = rules
            if i <= 10:
                m = _SUPPRESS_FILE_RE.search(line)
                if m:
                    self._file_suppress |= {r.strip() for r in m.group(1).split(",")}

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_suppress:
            return True
        return rule in self._line_suppress.get(line, set())

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""


@dataclass
class LintConfig:
    """Anchors the cross-file rules. Tests point these at fixtures."""

    root: str
    paths: Tuple[str, ...] = ("dynamo_tpu",)
    # MET001 anchors: the module holding COUNTER_KEYS/GAUGE_KEYS and the
    # Grafana dashboard whose exprs must pin them.
    aggregator_path: str = "dynamo_tpu/metrics_aggregator.py"
    grafana_path: str = "deploy/grafana/dynamo_tpu_serving.json"
    # SYNC001 anchor: hot-path spec + the sanctioned sync allowlist.
    sync_allowlist_path: str = "tools/dtlint/sync_allowlist.json"
    # THR001: (file-suffix, qualname) pairs designated as extra thread entry
    # points beyond auto-detected threading.Thread targets.
    thread_entries: Tuple[Tuple[str, str], ...] = (
        # Engine stats handler runs on the event loop while the scheduler
        # steps on a worker thread; these scrape-side entry points share
        # state with the step path.
        ("dynamo_tpu/engine/engine.py", "TpuEngine.stats_handler"),
        ("dynamo_tpu/engine/scheduler.py", "Scheduler.metrics"),
        ("dynamo_tpu/engine/scheduler.py", "Scheduler.kv_gauges"),
        ("dynamo_tpu/engine/scheduler.py", "Scheduler.debug_state"),
        ("dynamo_tpu/runtime/telemetry.py", "StallWatchdog.check"),
    )
    # MET001: functions whose dict keys are worker-scrape wire keys, and
    # path fragments OUTSIDE the worker-scrape plane (router/frontend
    # metrics have their own registries and conventions; the planner's
    # controller.to_stats IS on the scrape wire since PR 11, so planner/
    # is in scope).
    met001_emitters: Tuple[str, ...] = (
        "to_wire", "to_stats", "stats_handler", "kv_gauges", "stats",
        "_stats_loop",
    )
    met001_exclude: Tuple[str, ...] = (
        "llm/kv_router", "llm/http", "deploy/", "runtime/metrics.py",
    )
    # WARM001: files whose record_exec dispatch sites define the serving
    # key space, and the function that must register each kind at warmup.
    warmup_scopes: Tuple[str, ...] = (
        "dynamo_tpu/engine/scheduler.py", "dynamo_tpu/engine/models/llama.py",
    )
    warmup_func: str = "Scheduler.warmup"
    # ASYNC001: path fragments whose ``async def`` bodies serve traffic —
    # a blocking call reachable from one stalls every request on the loop.
    async_scopes: Tuple[str, ...] = (
        "dynamo_tpu/frontend.py", "dynamo_tpu/llm/http/",
        "dynamo_tpu/runtime/component.py", "dynamo_tpu/runtime/push_router.py",
        "dynamo_tpu/runtime/health.py", "dynamo_tpu/planner/fleet.py",
        "dynamo_tpu/planner/observer.py", "dynamo_tpu/llm/mocker.py",
        "dynamo_tpu/llm/disagg.py", "dynamo_tpu/llm/migration.py",
        "dynamo_tpu/engine/engine.py", "dynamo_tpu/llm/preprocessor.py",
    )
    # WIRE001: who writes request fields onto the wire and who reads them
    # off. Entries are function-scoped ("path::qualname") because receiver
    # names collide across protocol layers — the preprocessor's ``request``
    # parameter is the OpenAI body in transform_request but the wire dict in
    # transform_response. The stop_* pairs anchor the nested stop_conditions
    # sub-channel whose writer and reader live three hops apart.
    wire_writers: Tuple[str, ...] = (
        "dynamo_tpu/llm/protocols/common.py::PreprocessedRequest.to_wire",
        "dynamo_tpu/llm/preprocessor.py::OpenAIPreprocessor.transform_request",
        "dynamo_tpu/llm/preprocessor.py::OpenAIPreprocessor.preprocess",
        "dynamo_tpu/llm/disagg.py::DisaggDecodeHandler.generate",
        "dynamo_tpu/llm/migration.py::_MigrationEngine._fold",
        "dynamo_tpu/llm/multimodal.py::EncodeOperator.transform_request",
        # Response direction: engine/mocker output frames and their schema.
        "dynamo_tpu/llm/protocols/common.py::LLMEngineOutput.to_wire",
        "dynamo_tpu/engine/engine.py::TpuEngine.generate",
        "dynamo_tpu/llm/mocker.py::MockTpuEngine._sim_loop",
    )
    wire_readers: Tuple[str, ...] = (
        "dynamo_tpu/engine/engine.py::TpuEngine.generate",
        "dynamo_tpu/llm/mocker.py::MockTpuEngine.generate",
        "dynamo_tpu/llm/backend.py::Backend.transform_request",
        "dynamo_tpu/llm/backend.py::Backend.transform_response",
        "dynamo_tpu/llm/protocols/common.py::PreprocessedRequest.from_wire",
        "dynamo_tpu/llm/protocols/common.py::LLMEngineOutput.from_wire",
        "dynamo_tpu/llm/preprocessor.py::OpenAIPreprocessor.transform_response",
        "dynamo_tpu/llm/migration.py::_MigrationEngine.generate",
        "dynamo_tpu/llm/migration.py::_MigrationEngine._fold",
        "dynamo_tpu/llm/disagg.py::DisaggDecodeHandler.generate",
        "dynamo_tpu/llm/kv_router/__init__.py::KvPushRouter.generate",
    )
    wire_stop_writers: Tuple[str, ...] = (
        "dynamo_tpu/llm/protocols/openai.py::stop_conditions_from_request",
    )
    wire_stop_readers: Tuple[str, ...] = (
        "dynamo_tpu/engine/scheduler.py::StopConditions.from_dict",
    )
    # WIRE001 mocker parity: the mocker's stats families must be a subset
    # of the real engine plane's.
    mocker_path: str = "dynamo_tpu/llm/mocker.py"

    def abspath(self, rel: str) -> str:
        return os.path.join(self.root, rel)


class ProjectIndex:
    """All parsed modules under config.paths, plus lazy per-rule caches."""

    def __init__(self, config: LintConfig) -> None:
        self.config = config
        self.modules: List[SourceModule] = []
        seen = set()
        for p in config.paths:
            ap = config.abspath(p)
            if os.path.isfile(ap) and p.endswith(".py"):
                if p not in seen:
                    seen.add(p)
                    self.modules.append(SourceModule(config.root, p))
                continue
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn), config.root)
                    rel = rel.replace(os.sep, "/")
                    if rel not in seen:
                        seen.add(rel)
                        self.modules.append(SourceModule(config.root, rel))

    def module(self, relpath: str) -> Optional[SourceModule]:
        for m in self.modules:
            if m.relpath == relpath or m.relpath.endswith("/" + relpath):
                return m
        return None


# --- rule registry ----------------------------------------------------------

RULES: Dict[str, Callable[[ProjectIndex], List[Finding]]] = {}
RULE_DOCS: Dict[str, str] = {}


def rule(name: str, doc: str):
    def deco(fn):
        RULES[name] = fn
        RULE_DOCS[name] = doc
        return fn
    return deco


# --- baseline ---------------------------------------------------------------

class BaselineError(ValueError):
    pass


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        for req in ("rule", "file", "qualname", "key", "reason"):
            if not e.get(req):
                raise BaselineError(f"baseline entry missing '{req}': {e}")
    return entries


def apply_baseline(
    findings: List[Finding], entries: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """(unbaselined findings, stale entries). An entry absorbs at most
    one finding per (rule,file,qualname,key) identity — but identical
    identities (e.g. two device_get sites in one function) collapse onto
    one entry, so matching is by identity set, not 1:1 counting."""
    idents = {(e["rule"], e["file"], e["qualname"], e["key"]): e for e in entries}
    live = set()
    out = []
    for f in findings:
        if f.ident() in idents:
            live.add(f.ident())
        else:
            out.append(f)
    stale = [e for ident, e in idents.items() if ident not in live]
    return out, stale


# --- shared AST helpers -----------------------------------------------------

def dotted(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def iter_functions(tree: ast.Module) -> Iterable[Tuple[str, ast.AST]]:
    """Yield (qualname, funcdef) for every function/method, including
    nested ones ('outer.<locals>.inner' collapses to 'outer.inner')."""

    def walk(node: ast.AST, prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child
                yield from walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def enclosing_map(tree: ast.Module) -> Dict[int, str]:
    """{line: qualname} for every line covered by a function body (innermost
    wins) — lets rules attribute a Finding to its enclosing function."""
    spans: List[Tuple[int, int, str]] = []
    for q, fn in iter_functions(tree):
        end = getattr(fn, "end_lineno", fn.lineno)
        spans.append((fn.lineno, end, q))
    spans.sort(key=lambda s: (s[0], -s[1]))
    out: Dict[int, str] = {}
    for lo, hi, q in spans:
        for ln in range(lo, hi + 1):
            out[ln] = q  # later (inner) spans overwrite outer ones
    return out


def qualname_at(line_map: Dict[int, str], line: int) -> str:
    return line_map.get(line, "<module>")


def module_constants(tree: ast.Module) -> Dict[str, object]:
    """Module-level NAME = <literal> bindings (tuples/lists of str, str,
    int) — used to expand f-string metric keys and spot mutable globals."""
    out: Dict[str, object] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            try:
                out[node.targets[0].id] = ast.literal_eval(node.value)
            except (ValueError, SyntaxError):
                pass
    return out


@dataclass
class LintResult:
    findings: List[Finding]
    stale_baseline: List[dict] = field(default_factory=list)
    baseline_size: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline


def run_lint(
    config: LintConfig,
    rules: Optional[Iterable[str]] = None,
    baseline_path: Optional[str] = None,
) -> LintResult:
    # Import registers the rules (they live in sibling modules).
    from tools.dtlint import (  # noqa: F401
        rules_async, rules_jit, rules_leak, rules_metrics, rules_sync,
        rules_threads, rules_warmup, rules_wire,
    )

    index = ProjectIndex(config)
    names = list(rules) if rules else sorted(RULES)
    findings: List[Finding] = []
    for name in names:
        if name not in RULES:
            raise ValueError(f"unknown rule {name!r}; have {sorted(RULES)}")
        findings.extend(RULES[name](index))
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    stale: List[dict] = []
    size = 0
    if baseline_path:
        entries = load_baseline(baseline_path)
        size = len(entries)
        if rules:
            entries = [e for e in entries if e["rule"] in set(names)]
        findings, stale = apply_baseline(findings, entries)
    return LintResult(findings=findings, stale_baseline=stale, baseline_size=size)
