"""Decompose decode-step time: per-layer cost vs per-step frame overhead.

Runs decode_multi at several layer counts (same shapes otherwise); the slope
is the true per-layer cost (weights + KV + attention for one layer), the
intercept is the step frame (embed lookup, final norm, lm_head, sampling,
window bookkeeping). Compares the slope against the HBM floor for one
layer's bytes to see how far the layer body is from bandwidth-bound.

Usage: python tools/profile_decode_split.py [batch] [ctx]
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import get_config
from dynamo_tpu.engine.kv_cache import KvCacheArrays
from dynamo_tpu.engine.models import llama

batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
ctx_len = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
window, steps = 16, 128
HBM = 856.0

base = get_config("llama-3.2-1b").replace(max_seq_len=4096)


def measure(num_layers):
    cfg = base.replace(num_layers=num_layers)
    params = llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    pbytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    num_blocks = batch * (ctx_len // cfg.block_size + 4) + 8
    cache = KvCacheArrays.create(cfg, num_blocks=num_blocks, dtype=jnp.bfloat16)
    needed = (ctx_len + steps + 1 + cfg.block_size - 1) // cfg.block_size
    w = (needed + 15) // 16 * 16
    tables = jnp.tile(jnp.arange(1, w + 1, dtype=jnp.int32)[None, :], (batch, 1))
    tables = (tables + jnp.arange(batch, dtype=jnp.int32)[:, None] * (ctx_len // cfg.block_size)) % (num_blocks - 1) + 1
    active = jnp.ones((batch,), dtype=bool)
    zf, zi, of = jnp.zeros((batch,), jnp.float32), jnp.zeros((batch,), jnp.int32), jnp.ones((batch,), jnp.float32)
    fn = jax.jit(
        lambda p, k, v, t, pos, key: llama.decode_multi(
            p, cfg, k, v, t, pos, tables, active, zf, zi, of, key, window
        ),
        donate_argnums=(1, 2),
    )
    toks = jnp.zeros((batch,), jnp.int32)
    pos = jnp.full((batch,), ctx_len, jnp.int32)
    k, v = cache.k, cache.v
    out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(0))
    np.asarray(out)
    nw = max(1, steps // window)
    t0 = time.perf_counter()
    for i in range(nw):
        out, k, v = fn(params, k, v, toks, pos, jax.random.PRNGKey(i))
    np.asarray(out)
    dt = (time.perf_counter() - t0) / (nw * window)
    return dt, pbytes


points = []
for L in (2, 4, 8, 16):
    dt, pbytes = measure(L)
    print(f"L={L:3d}: {dt*1e3:7.3f} ms/step (params {pbytes/1e9:.2f} GB)", flush=True)
    points.append((L, dt))

(l1, t1), (l2, t2) = points[0], points[-1]
slope = (t2 - t1) / (l2 - l1)
intercept = t1 - slope * l1
kv_layer = 2 * ctx_len * 512 * 2 * batch
w_layer = (2048 * (2048 + 512 * 2 + 2048) + 3 * 2048 * 8192) * 2  # qkvo + mlp bf16
floor = (kv_layer + w_layer) / HBM / 1e9
embed_bytes = 128256 * 2048 * 2
print(f"\nper-layer: {slope*1e3:.3f} ms (HBM floor {floor*1e3:.3f} ms -> {100*floor/slope:.0f}% eff)")
print(f"step frame: {intercept*1e3:.3f} ms (lm_head read floor {embed_bytes/HBM/1e9*1e3:.3f} ms)")
