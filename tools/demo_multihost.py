"""Two-PROCESS multi-host serving demo (ref: MultiNodeConfig engines.rs:28).

Everything here is the real production path, exercised across actual OS
processes rather than simulated in one:

  parent ──spawns──► control-plane broker (python -m dynamo_tpu.control_plane)
         ──spawns──► worker rank? ┐ DYN_CONTROL_PLANE=tcp
         ──spawns──► worker rank? ┘ (ranks assigned by store rendezvous)

Each worker connects a DistributedRuntime to the broker, wins a rank via
``multihost.rendezvous`` (create-only store puts), joins the jax
multi-controller runtime (``jax.distributed.initialize`` — rank 0's
coordinator address travels through the control plane), builds ONE global
dp×tp mesh over both processes' devices (dp crosses the process/DCN
boundary, tp stays inside), shards real llama params + paged KV over it,
and executes the same sharded decode step SPMD. CPU backend with 4
virtual devices per process → an 8-device global mesh, per the repo's
multi-chip testing convention.

Prints ONE JSON line; ``--write-artifact`` also records it to
MULTIHOST_DEMO_r05.json for the round artifact.

Usage: python tools/demo_multihost.py [--write-artifact]
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GROUP = "demo2p"
NPROC = 2
LOCAL_DEVICES = 4


def _worker() -> None:
    import asyncio

    async def main():
        from dynamo_tpu.engine.multihost import init_multihost, rendezvous
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        import jax

        # The axon PJRT plugin overrides JAX_PLATFORMS (see tests/conftest.py)
        # — force the CPU backend via config BEFORE any backend touch.
        jax.config.update("jax_platforms", "cpu")

        drt = await DistributedRuntime.from_settings()
        mh = await rendezvous(drt, GROUP, NPROC)
        init_multihost(mh)  # joins the jax multi-controller runtime
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dynamo_tpu.engine.config import get_config
        from dynamo_tpu.engine.models import llama
        from dynamo_tpu.engine.multihost import build_multihost_mesh
        from dynamo_tpu.engine.sharding import ParallelConfig, kv_cache_spec, param_specs

        assert jax.device_count() == NPROC * LOCAL_DEVICES, jax.device_count()
        par = ParallelConfig(tp=LOCAL_DEVICES)
        mesh = build_multihost_mesh(par, dcn_dp=NPROC)  # dp crosses processes

        cfg = get_config("tiny")
        specs = param_specs(cfg.tie_word_embeddings)
        p_sh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
        params = jax.jit(
            lambda: llama.init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
            out_shardings=p_sh,
        )()

        B, blocks, width = 4, 16, 8
        kv_sh = NamedSharding(mesh, kv_cache_spec(cfg.num_kv_heads, par.tp))
        bt_sh = NamedSharding(mesh, P("dp"))
        shape = (cfg.num_layers, blocks, cfg.block_size, cfg.num_kv_heads, cfg.head_dim)
        k0, v0, toks, pos, tables, active = jax.jit(
            lambda: (
                jnp.zeros(shape, jnp.float32),
                jnp.zeros(shape, jnp.float32),
                jnp.ones((B,), jnp.int32) * 5,
                jnp.ones((B,), jnp.int32) * 20,
                jnp.tile(jnp.arange(1, width + 1, dtype=jnp.int32)[None], (B, 1)),
                jnp.ones((B,), bool),
            ),
            out_shardings=(kv_sh, kv_sh, bt_sh, bt_sh, bt_sh, bt_sh),
        )()

        @jax.jit
        def step(p, k, v, t, pos, bt, act):
            logits, k2, v2 = llama.decode(p, cfg, k, v, t, pos, bt, act)
            return jnp.sum(logits.astype(jnp.float32)), k2, v2

        s, k1, v1 = step(params, k0, v0, toks, pos, tables, active)
        s2, _, _ = step(params, k1, v1, toks, pos + 1, tables, active)
        result = {
            "process": mh.process_id,
            "num_processes": mh.num_processes,
            "coordinator": mh.coordinator,
            "global_devices": jax.device_count(),
            "local_devices": jax.local_device_count(),
            "mesh": {ax: int(n) for ax, n in mesh.shape.items()},
            "logits_sum_step1": float(s),
            "logits_sum_step2": float(s2),
        }
        print("MULTIHOST_WORKER " + json.dumps(result), flush=True)
        await drt.shutdown()

    asyncio.run(main())


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> None:
    port = _free_port()
    broker = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.control_plane", "--host", "127.0.0.1", "--port", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
    )
    try:
        # Wait for the broker to listen.
        deadline = time.time() + 20
        up = False
        while time.time() < deadline:
            try:
                with socket.create_connection(("127.0.0.1", port), timeout=0.5):
                    up = True
                    break
            except OSError:
                time.sleep(0.2)
        if not up:
            broker.kill()
            out, _ = broker.communicate(timeout=10)
            raise RuntimeError(f"control-plane broker never listened: {out.strip()[-400:]}")

        env = dict(os.environ)
        env.update({
            "DYN_CONTROL_PLANE": "tcp",
            "DYN_CONTROL_PLANE_ADDRESS": f"127.0.0.1:{port}",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": f"--xla_force_host_platform_device_count={LOCAL_DEVICES}",
        })
        workers = [
            subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--as-worker"],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO,
            )
            for _ in range(NPROC)
        ]
        results = []
        ok = True
        try:
            for w in workers:
                out, _ = w.communicate(timeout=240)
                found = None
                for line in out.splitlines():
                    if line.startswith("MULTIHOST_WORKER "):
                        found = json.loads(line[len("MULTIHOST_WORKER "):])
                if found is None or w.returncode != 0:
                    ok = False
                    results.append({"rc": w.returncode, "tail": out.strip()[-400:]})
                else:
                    results.append(found)
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()

        sums = {(r.get("logits_sum_step1"), r.get("logits_sum_step2")) for r in results if "process" in r}
        all_ok = ok and len([r for r in results if "process" in r]) == NPROC
        artifact = {
            "ok": all_ok and len(sums) == 1,
            "processes": NPROC,
            "local_devices_per_process": LOCAL_DEVICES,
            # Only meaningful when every worker completed; a lone survivor
            # must not read as a verified cross-process comparison.
            "spmd_results_identical": all_ok and len(sums) == 1,
            "workers": results,
        }
        print(json.dumps(artifact))
        if "--write-artifact" in sys.argv:
            with open(os.path.join(REPO, "MULTIHOST_DEMO_r05.json"), "w") as f:
                json.dump(artifact, f, indent=1)
        sys.exit(0 if artifact["ok"] else 1)
    finally:
        broker.terminate()


if __name__ == "__main__":
    if "--as-worker" in sys.argv:
        _worker()
    else:
        main()
